"""ISSUE 19 tests: the Pallas-by-default GBDT compute tier, on CPU.

Every kernel in the tier carries a Pallas interpret-mode path, so this
suite executes the ACTUAL kernel bodies (route+hist, split finder, fused
scoring walk, int8 dequant matmul) under `JAX_PLATFORMS=cpu` — not a
shadow implementation. The contracts under test (docs/gbdt.md "Pallas
compute tier"):

- route+hist is EXACT: trees grown under ``hist_impl="pallas"`` are
  bit-identical to ``hist_impl="einsum"`` on every engine — masked
  padding rows carry zero weight and add 0.0f to every histogram cell;
- the split-finder kernel makes IDENTICAL decisions (feature, threshold,
  same first-max/first-argmax tie-breaking) with gains in an f32-ulp
  band, and silently defers to the reference impl when any feature is
  categorical;
- fused Pallas scoring is bitwise identical to the reference walk,
  including NaN routing and multiclass ensembles;
- int8 weight-only quantization: per-channel codes within the documented
  error bound, the dequant-in-VMEM matmul against the XLA factorization,
  and the parity-gated network dispatch;
- checkpoint fingerprints: einsum fits keep pre-PR19 byte-identical
  fingerprints, pallas fits refuse to resume onto einsum segments on any
  engine, and streamed fits keep the PR 15 ``stream_hist_impl`` key NAME.

TPU-hardware behavior (auto->pallas resolution, compiled-kernel parity,
MFU attribution deltas) lives in tests/test_tpu_kernels.py.
"""

import dataclasses

import numpy as np
import pytest

from mmlspark_tpu.gbdt import trainer as trainer_mod
from mmlspark_tpu.gbdt.objectives import make_objective
from mmlspark_tpu.gbdt.trainer import (
    TrainConfig,
    _gbdt_fingerprint,
    _resolve_hist_impl,
    train_booster,
)

OBJ = make_objective("binary", num_class=2)


def _data(n=768, f=10, seed=0, cat=False):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, f))
    if cat:
        x[:, f - 1] = rng.integers(0, 7, n)
    y = ((x[:, 0] + 0.5 * x[:, 1] - 0.3 * x[:, 2]
          + rng.normal(scale=0.5, size=n)) > 0).astype(np.float64)
    return x, y


def _fit(x, y, engine, hist_impl, stream=0, single=False, **cfg_kw):
    cfg = TrainConfig(num_iterations=3, num_leaves=7, max_bin=31,
                      verbosity=0, engine=engine, hist_impl=hist_impl,
                      **cfg_kw)
    if single:
        trainer_mod._FORCE_SINGLE_DEVICE = True
    try:
        return train_booster(x, y, OBJ, cfg, stream_chunk_rows=stream)
    finally:
        trainer_mod._FORCE_SINGLE_DEVICE = False


# -- hist_impl resolution ------------------------------------------------------


class TestHistImplResolution:
    def test_unknown_impl_raises(self):
        with pytest.raises(ValueError, match="hist_impl"):
            _resolve_hist_impl(TrainConfig(hist_impl="cuda"), "fused")

    def test_auto_resolves_einsum_off_tpu(self):
        """On this CPU backend auto keeps the einsum rollback default on
        every engine — interpret-mode kernels are a parity vehicle, not a
        win, so they must be asked for explicitly."""
        cfg = TrainConfig(hist_impl="auto")
        for engine in ("fused", "data_parallel"):
            assert _resolve_hist_impl(cfg, engine) == "einsum"

    def test_explicit_pick_is_honored(self):
        for impl in ("pallas", "einsum"):
            cfg = TrainConfig(hist_impl=impl)
            assert _resolve_hist_impl(cfg, "data_parallel") == impl

    def test_pick_pinned_once_in_trained_config(self):
        """train_booster resolves auto before any dispatch, so checkpoint
        segments and flight-record attrs all see the pinned value."""
        x, y = _data(n=256)
        b = _fit(x, y, "fused", "auto", single=True)
        assert b is not None  # the fit ran; resolution didn't raise


# -- route+hist kernel: trees bit-identical per engine -------------------------


class TestRouteHistParity:
    def _pair(self, **kw):
        x, y = _data()
        bp = _fit(x, y, hist_impl="pallas", **kw)
        be = _fit(x, y, hist_impl="einsum", **kw)
        return bp.model_to_string(), be.model_to_string()

    def test_fused_trees_bit_identical(self):
        p, e = self._pair(engine="fused", single=True)
        assert p == e

    def test_data_parallel_trees_bit_identical(self):
        """The dp engine pads each shard up to a hist-block multiple under
        pallas (n=768 on the 8-way mesh -> 96-row shards padded to 2048);
        the masked pad rows must not move a single bit."""
        p, e = self._pair(engine="data_parallel")
        assert p == e

    def test_streamed_trees_bit_identical(self):
        # chunk size deliberately NOT a block multiple: exercises the pad
        p, e = self._pair(engine="data_parallel", stream=300)
        assert p == e

    def test_categorical_fit_survives_pallas_pick(self):
        """Categorical features keep the reference split machinery (the
        kernel is numeric-only) while route+hist stays kernelized — the
        mixed fit must still match einsum bit-for-bit."""
        x, y = _data(cat=True)
        kw = dict(categorical_indexes=(x.shape[1] - 1,))
        bp = _fit(x, y, "fused", "pallas", single=True, **kw)
        be = _fit(x, y, "fused", "einsum", single=True, **kw)
        assert bp.model_to_string() == be.model_to_string()


# -- Pallas split finder -------------------------------------------------------


def _hists(m=8, f=16, b=16, seed=3):
    rng = np.random.default_rng(seed)
    cnt = rng.integers(1, 40, size=(m, f, b)).astype(np.float32)
    return np.stack([
        rng.normal(size=(m, f, b)).astype(np.float32) * cnt,
        rng.uniform(0.1, 1.0, size=(m, f, b)).astype(np.float32) * cnt,
        cnt,
    ], axis=-1)


def _find(hists, impl, cat=None, min_data=1.0, min_hess=1e-3):
    from mmlspark_tpu.gbdt.compute import best_splits_for_hists

    m, f, b, _ = hists.shape
    cat = tuple([False] * f) if cat is None else cat
    out = best_splits_for_hists(
        hists, True, np.full(f, b, np.int32),
        np.asarray(cat, bool), np.ones(f, bool),
        np.float32(min_data), np.float32(min_hess),
        np.float32(0.0), np.float32(1.0),
        num_bins=b, max_cat_threshold=8, cat_static=cat, split_impl=impl,
    )
    return [np.asarray(a) for a in out]


class TestSplitFinderKernel:
    def test_decisions_identical_gains_in_band(self):
        ref, ker = _find(_hists(), "reference"), _find(_hists(), "pallas")
        np.testing.assert_array_equal(ref[1], ker[1])  # feature
        np.testing.assert_array_equal(ref[2], ker[2])  # threshold bin
        np.testing.assert_allclose(ref[0], ker[0], rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(ref[4], ker[4])  # member mask
        # left/right stats feed leaf values — same ulp band as gains
        np.testing.assert_allclose(ref[5], ker[5], rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(ref[6], ker[6], rtol=1e-5, atol=1e-5)

    def test_tie_breaking_identical_on_duplicate_features(self):
        """Two byte-identical feature histograms produce an exact gain
        tie; both impls must pick the FIRST feature (and the first
        maximizing threshold within it) — the documented tie-break rule."""
        h = _hists(m=4, f=6)
        h[:, 3] = h[:, 1]  # exact duplicate -> guaranteed argmax tie
        ref, ker = _find(h, "reference"), _find(h, "pallas")
        np.testing.assert_array_equal(ref[1], ker[1])
        np.testing.assert_array_equal(ref[2], ker[2])

    def test_min_data_min_hess_filtering_identical(self):
        h = _hists(seed=5)
        ref = _find(h, "reference", min_data=60.0, min_hess=20.0)
        ker = _find(h, "pallas", min_data=60.0, min_hess=20.0)
        np.testing.assert_array_equal(ref[1], ker[1])
        np.testing.assert_array_equal(ref[2], ker[2])
        # invalid-everywhere leaves gate identically (gain <= 0 both arms)
        np.testing.assert_array_equal(ref[0] > 0, ker[0] > 0)

    def test_categorical_falls_back_to_reference(self):
        """Any categorical feature routes the WHOLE call to the reference
        impl — outputs are equal to the reference's exactly (same code)."""
        h = _hists(m=4, f=6)
        cat = (False, True, False, False, False, False)
        ref = _find(h, "reference", cat=cat)
        ker = _find(h, "pallas", cat=cat)
        for a, b in zip(ref, ker):
            np.testing.assert_array_equal(a, b)


# -- fused Pallas scoring ------------------------------------------------------


class TestScoringKernel:
    def _booster(self, cat=False, multiclass=False):
        x, y = _data(cat=cat, seed=7)
        if cat:
            # the categorical slot must actually drive the label, or no
            # tree ever takes a categorical split and has_cat stays False
            y = np.where(np.isin(x[:, -1], (1, 4, 6)),
                         1.0 - y, y)
        if multiclass:
            rng = np.random.default_rng(8)
            y = rng.integers(0, 3, x.shape[0]).astype(np.float64)
            obj = make_objective("multiclass", num_class=3)
        else:
            obj = OBJ
        cfg = TrainConfig(num_iterations=3, num_leaves=7, max_bin=31,
                          verbosity=0,
                          categorical_indexes=(x.shape[1] - 1,) if cat
                          else ())
        trainer_mod._FORCE_SINGLE_DEVICE = True
        try:
            return train_booster(x, y, obj, cfg), x
        finally:
            trainer_mod._FORCE_SINGLE_DEVICE = False

    def _walk(self, b, x, impl):
        b._walk_impl = impl
        try:
            return np.asarray(b.predict_raw(x.astype(np.float32)))
        finally:
            b._walk_impl = "auto"

    def test_kernel_walk_bitwise_identical(self):
        b, x = self._booster()
        assert np.array_equal(self._walk(b, x, "pallas"),
                              self._walk(b, x, "raw"))

    def test_nan_features_route_left_identically(self):
        b, x = self._booster()
        x = x.copy()
        x[::3, 0] = np.nan  # NaN goes left — both walks, same bit pattern
        assert np.array_equal(self._walk(b, x, "pallas"),
                              self._walk(b, x, "raw"))

    def test_multiclass_bitwise_identical(self):
        b, x = self._booster(multiclass=True)
        assert np.array_equal(self._walk(b, x, "pallas"),
                              self._walk(b, x, "raw"))

    def test_categorical_ensemble_keeps_reference_walk(self):
        """has_cat ensembles must take the reference walk even under a
        forced pallas pick (the kernel table is numeric-only) — and still
        score correctly."""
        b, x = self._booster(cat=True)
        assert b._packed_device()["has_cat"]
        assert np.array_equal(self._walk(b, x, "pallas"),
                              self._walk(b, x, "raw"))

    def test_auto_resolves_raw_off_tpu(self):
        import jax

        assert jax.default_backend() != "tpu"
        b, x = self._booster()
        # auto == raw bit-for-bit here (they are the same branch on CPU)
        assert np.array_equal(self._walk(b, x, "auto"),
                              self._walk(b, x, "raw"))


# -- int8 quantization ---------------------------------------------------------


class TestInt8Quant:
    def test_per_channel_codes_and_error_bound(self):
        from mmlspark_tpu.dnn.quant import dequantize, quantize_per_channel

        rng = np.random.default_rng(0)
        w = rng.normal(size=(64, 32)).astype(np.float32)
        w[:, 5] = 0.0  # all-zero channel
        q, scale = quantize_per_channel(w)
        assert q.dtype == np.int8 and scale.shape == (32,)
        assert np.abs(q).max() <= 127
        assert scale[5] == 1.0  # zero channel dequantizes exactly
        # documented bound: per-weight error <= scale/2 per channel
        err = np.abs(dequantize(q, scale) - w)
        assert np.all(err <= scale[None, :] / 2 + 1e-7)

    def test_kernel_matches_xla_factorization(self):
        from mmlspark_tpu.dnn.quant import int8_matmul, quantize_per_channel

        rng = np.random.default_rng(1)
        x = rng.normal(size=(48, 200)).astype(np.float32)
        q, scale = quantize_per_channel(
            rng.normal(size=(200, 96)).astype(np.float32))
        got = np.asarray(int8_matmul(x, q, scale))
        want = (x @ q.astype(np.float32)) * scale[None, :]
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_oversized_operand_falls_back_to_xla(self):
        """Past the VMEM budget the impl IS the XLA factorization — the
        two paths agree because the fallback is the reference formula."""
        from mmlspark_tpu.dnn import quant

        rng = np.random.default_rng(2)
        K, N = 256, 8192  # K_pad*N_pad = 2M > _MM_VMEM_ELEMS (1M)
        assert K * N > quant._MM_VMEM_ELEMS
        x = rng.normal(size=(8, K)).astype(np.float32)
        q, scale = quant.quantize_per_channel(
            rng.normal(size=(K, N)).astype(np.float32))
        got = np.asarray(quant.int8_matmul(x, q, scale))
        want = (x @ q.astype(np.float32)) * scale[None, :]
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_quantize_variables_tree_shape(self):
        from mmlspark_tpu.dnn.quant import quantize_variables

        variables = {
            "params": {
                "d0": {"kernel": np.ones((4, 3), np.float32),
                       "bias": np.zeros(3, np.float32)},
                "bn0": {"scale": np.ones(3, np.float32)},
            },
            "state": {"bn0": {"mean": np.zeros(3, np.float32)}},
        }
        out = quantize_variables(variables)
        d0 = out["params"]["d0"]
        assert d0["kernel"].dtype == np.int8
        assert d0["kernel_scale"].shape == (3,)
        assert d0["bias"].dtype == np.float32  # biases stay f32
        assert "kernel_scale" not in out["params"]["bn0"]
        assert out["state"] == variables["state"]  # state untouched


# -- checkpoint fingerprints ---------------------------------------------------


def _fp(cfg=None, stream=0, hist_impl=None, n=64):
    x, y = _data(n=n, seed=11)
    cfg = cfg or TrainConfig(num_iterations=3, verbosity=0)
    return _gbdt_fingerprint(x, y, OBJ, cfg, None, None, None, None,
                             stream_chunk_rows=stream, hist_impl=hist_impl)


class TestHistImplFingerprints:
    def test_einsum_keeps_legacy_fingerprint_byte_identical(self):
        """The back-compat contract: an einsum fit's fingerprint is
        byte-identical to a pre-PR19 store's (which never saw the field),
        so every existing checkpoint keeps resuming."""
        assert _fp(hist_impl="einsum") == _fp(hist_impl=None)
        assert _fp(stream=300, hist_impl="einsum") == _fp(stream=300)

    def test_pallas_differs_from_einsum_on_every_engine(self):
        """hist_impl is resolved before engine dispatch and the engine
        key itself is popped from the ident — so the pallas/einsum split
        shows on plain, streamed, and (via the same ident) dp fits."""
        assert _fp(hist_impl="pallas") != _fp(hist_impl="einsum")
        assert _fp(stream=300, hist_impl="pallas") != _fp(stream=300,
                                                          hist_impl="einsum")

    def test_cfg_field_itself_is_popped(self):
        """Only the RESOLVED impl is identity-bearing: a cfg carrying
        hist_impl='pallas' that resolved to einsum (the auto GSPMD
        carve-out) must fingerprint as einsum."""
        cfg_p = TrainConfig(num_iterations=3, verbosity=0,
                            hist_impl="pallas")
        cfg_e = TrainConfig(num_iterations=3, verbosity=0,
                            hist_impl="einsum")
        assert _fp(cfg=cfg_p, hist_impl="einsum") == _fp(cfg=cfg_e,
                                                         hist_impl="einsum")

    def test_streamed_fits_keep_pr15_key_name(self, monkeypatch):
        """Streamed pallas stores written before the per-engine
        generalization carry `stream_hist_impl`; the generalized emitter
        must keep that NAME under streaming (so they keep resuming) and
        use `hist_impl` only for non-streamed fits."""
        from mmlspark_tpu.io import checkpoint as ckpt_mod

        captured = {}
        real = ckpt_mod.fingerprint

        def spy(ident, *arrays, **kw):
            captured.update(ident)
            return real(ident, *arrays, **kw)

        monkeypatch.setattr(ckpt_mod, "fingerprint", spy)

        captured.clear()
        _fp(stream=300, hist_impl="pallas")
        assert captured.get("stream_hist_impl") == "pallas"
        assert "hist_impl" not in captured

        captured.clear()
        _fp(hist_impl="pallas")
        assert captured.get("hist_impl") == "pallas"
        assert "stream_hist_impl" not in captured

        captured.clear()
        _fp(hist_impl="einsum")
        assert "hist_impl" not in captured
        assert "stream_hist_impl" not in captured

    def test_pallas_store_refuses_einsum_resume(self, tmp_path):
        """End to end through the checkpoint store: a pallas-grown store
        must refuse a resume under einsum segments (and a changed impl
        must refuse rather than silently mix kernels mid-ensemble)."""
        x, y = _data(n=256, seed=13)

        def run(impl):
            cfg = TrainConfig(num_iterations=4, num_leaves=7, max_bin=31,
                              verbosity=0, engine="fused", hist_impl=impl)
            trainer_mod._FORCE_SINGLE_DEVICE = True
            try:
                return train_booster(x, y, OBJ, cfg,
                                     checkpoint_dir=str(tmp_path / "ck"),
                                     checkpoint_every=2)
            finally:
                trainer_mod._FORCE_SINGLE_DEVICE = False

        run("pallas")
        with pytest.raises(ValueError, match="fingerprint"):
            run("einsum")


# -- estimator Params ----------------------------------------------------------


class TestEstimatorHistImplParam:
    def test_param_threads_to_train_config(self):
        from mmlspark_tpu.gbdt import LightGBMClassifier

        est = LightGBMClassifier(hist_impl="einsum")
        assert est._train_config(2).hist_impl == "einsum"
        assert LightGBMClassifier()._train_config(2).hist_impl == "auto"

    def test_bad_value_fails_at_fit_entry(self):
        with pytest.raises(ValueError, match="hist_impl"):
            x, y = _data(n=128)
            _fit(x, y, "fused", "metal", single=True)
