"""Tests: utility + data-prep stage zoo."""

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame, DataType
from mmlspark_tpu.stages import (
    Cacher,
    CheckpointData,
    ClassBalancer,
    CleanMissingData,
    DataConversion,
    DropColumns,
    EnsembleByKey,
    Explode,
    IndexToValue,
    Lambda,
    MultiColumnAdapter,
    PartitionConsolidator,
    PartitionSample,
    RenameColumn,
    Repartition,
    SelectColumns,
    SummarizeData,
    TextPreprocessor,
    Timer,
    UDFTransformer,
    ValueIndexer,
)


def _df():
    return DataFrame.from_dict(
        {
            "a": [1.0, 2.0, 3.0, 4.0],
            "b": ["x", "y", "x", "z"],
            "c": [10, 20, 30, 40],
        }
    )


def test_drop_select_rename():
    df = _df()
    assert DropColumns(["b"]).transform(df).columns == ["a", "c"]
    assert SelectColumns(["c", "a"]).transform(df).columns == ["c", "a"]
    out = RenameColumn("a", "alpha").transform(df)
    assert "alpha" in out.columns and "a" not in out.columns
    # schema dry-runs agree
    assert [f.name for f in DropColumns(["b"]).transform_schema(df.schema)] == ["a", "c"]


def test_repartition_and_consolidator():
    df = _df().repartition(4)
    assert Repartition(2).transform(df).num_partitions == 2
    assert PartitionConsolidator().transform(df).num_partitions == 1


def test_explode():
    df = DataFrame.from_dict(
        {"id": [1, 2], "words": [["a", "b"], ["c"]]},
        types={"words": DataType.ARRAY},
    )
    out = Explode("words", "word").transform(df)
    assert len(out) == 3
    assert list(out["word"]) == ["a", "b", "c"]
    assert list(out["id"]) == [1, 1, 2]


def test_lambda_and_udf():
    df = _df()
    lam = Lambda(lambda d: d.filter(d["a"] > 2.0))
    assert len(lam.transform(df)) == 2
    udf = UDFTransformer("b", "b_up", udf=str.upper)
    assert list(udf.transform(df)["b_up"]) == ["X", "Y", "X", "Z"]
    vec = UDFTransformer("a", "a2", vector_udf=lambda v: v * 2)
    np.testing.assert_array_equal(vec.transform(df)["a2"], df["a"] * 2)
    multi = UDFTransformer(
        output_col="ac", input_cols=["a", "c"], udf=lambda a, c: a + c
    )
    np.testing.assert_array_equal(multi.transform(df)["ac"], df["a"] + df["c"])


def test_timer_wraps_stage(caplog):
    df = _df()
    model = Timer(ValueIndexer("b", "b_idx")).fit(df)
    out = model.transform(df)
    assert "b_idx" in out.columns


def test_cacher_passthrough():
    df = _df()
    assert Cacher().transform(df) is df


def test_class_balancer():
    df = DataFrame.from_dict({"label": [0, 0, 0, 1]})
    model = ClassBalancer("label", "weight").fit(df)
    out = model.transform(df)
    np.testing.assert_allclose(out["weight"], [1.0, 1.0, 1.0, 3.0])


def test_text_preprocessor():
    df = DataFrame.from_dict({"t": ["Hello World", "goodbye world"]})
    tp = TextPreprocessor(
        map={"hello": "hi", "world": "earth"}, input_col="t", output_col="o"
    )
    assert list(tp.transform(df)["o"]) == ["hi earth", "goodbye earth"]


def test_clean_missing_data_modes():
    df = DataFrame.from_dict({"x": [1.0, np.nan, 3.0], "y": [np.nan, 4.0, 6.0]})
    model = CleanMissingData(["x", "y"], ["x", "y"], "Mean").fit(df)
    out = model.transform(df)
    np.testing.assert_allclose(out["x"], [1.0, 2.0, 3.0])
    np.testing.assert_allclose(out["y"], [5.0, 4.0, 6.0])
    med = CleanMissingData(["x"], ["x2"], "Median").fit(df).transform(df)
    assert med["x2"][1] == 2.0
    cus = CleanMissingData(["x"], ["x3"], "Custom", custom_value=-1.0).fit(df).transform(df)
    assert cus["x3"][1] == -1.0


def test_value_indexer_roundtrip():
    df = _df()
    model = ValueIndexer("b", "b_idx").fit(df)
    out = model.transform(df)
    assert out.dtype("b_idx") == DataType.DOUBLE
    assert len(set(out["b_idx"])) == 3
    back = IndexToValue("b_idx", "b_back").transform(out)
    assert list(back["b_back"]) == list(df["b"])
    # unseen value raises
    df2 = DataFrame.from_dict({"b": ["new"]})
    with pytest.raises(ValueError):
        model.transform(df2)


def test_data_conversion():
    df = _df()
    out = DataConversion(["a"], "integer").transform(df)
    assert out.dtype("a") == DataType.INT
    out = DataConversion(["c"], "string").transform(df)
    assert list(out["c"]) == ["10", "20", "30", "40"]
    out = DataConversion(["b"], "toCategorical").transform(df)
    assert "categorical" in out.metadata("b")
    out2 = DataConversion(["b"], "clearCategorical").transform(out)
    assert "categorical" not in out2.metadata("b")
    df3 = DataFrame.from_dict({"d": ["2020-01-02 03:04:05"]})
    out3 = DataConversion(["d"], "date").transform(df3)
    assert out3.dtype("d") == DataType.TIMESTAMP


def test_summarize_data():
    df = DataFrame.from_dict({"x": [1.0, 2.0, 3.0, np.nan], "s": ["a", "a", "b", None]})
    out = SummarizeData().transform(df)
    rows = {r["Feature"]: r for r in out.collect()}
    assert rows["x"]["Missing Value Count"] == 1.0
    assert rows["x"]["Mean"] == 2.0
    assert rows["x"]["Median"] == 2.0
    assert rows["s"]["Unique Value Count"] == 3.0  # a, b, None
    # flag gating
    slim = SummarizeData(basic=False, sample=False, percentiles=False).transform(df)
    assert "Mean" not in slim.columns


def test_partition_sample_modes():
    df = DataFrame.from_dict({"x": np.arange(100.0)})
    assert len(PartitionSample("Head", count=7).transform(df)) == 7
    samp = PartitionSample("RandomSample", percent=0.2, seed=1).transform(df)
    assert 5 < len(samp) < 40
    absolute = PartitionSample(
        "RandomSample", rs_mode="Absolute", count=30, seed=1
    ).transform(df)
    assert 15 < len(absolute) < 45
    parts = PartitionSample("AssignToPartition", num_parts=4).transform(df)
    assert set(parts["Partition"]) <= {0, 1, 2, 3}


def test_multi_column_adapter():
    df = _df().with_column("b2", ["p", "q", "p", "p"])
    adapter = MultiColumnAdapter(
        ValueIndexer(), input_cols=["b", "b2"], output_cols=["bi", "b2i"]
    )
    model = adapter.fit(df)
    out = model.transform(df)
    assert "bi" in out.columns and "b2i" in out.columns


def test_ensemble_by_key():
    df = DataFrame.from_dict(
        {
            "k": ["a", "a", "b"],
            "v": np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]]),
        }
    )
    out = EnsembleByKey(keys=["k"], cols=["v"], col_names=["vm"]).transform(df)
    assert len(out) == 2
    by_k = {r["k"]: r["vm"] for r in out.collect()}
    np.testing.assert_allclose(by_k["a"], [2.0, 3.0])
    # broadcast-back mode keeps all rows
    out2 = EnsembleByKey(
        keys=["k"], cols=["v"], col_names=["vm"], collapse_group=False
    ).transform(df)
    assert len(out2) == 3


def test_checkpoint_data_disk_roundtrip():
    df = _df()
    out = CheckpointData(disk_included=True).transform(df)
    assert out.columns == df.columns
    np.testing.assert_array_equal(out["a"], df["a"])


def test_stage_persistence_roundtrip(tmp_path):
    df = _df()
    model = ValueIndexer("b", "bi").fit(df)
    path = str(tmp_path / "vi")
    model.save(path)
    from mmlspark_tpu.stages import ValueIndexerModel

    loaded = ValueIndexerModel.load(path)
    np.testing.assert_array_equal(loaded.transform(df)["bi"], model.transform(df)["bi"])


def test_time_interval_minibatch():
    """Over a materialized frame the interval batcher reduces to dynamic
    batching bounded by max_batch_size; FlattenBatch inverts it."""
    from mmlspark_tpu.stages.batching import (
        FlattenBatch,
        TimeIntervalMiniBatchTransformer,
    )

    df = DataFrame.from_dict({"x": np.arange(10.0)})
    batched = TimeIntervalMiniBatchTransformer(
        millis_to_wait=5, max_batch_size=4
    ).transform(df)
    sizes = [len(b) for b in batched["x"]]
    assert sum(sizes) == 10
    assert max(sizes) <= 4
    flat = FlattenBatch().transform(batched)
    np.testing.assert_allclose(flat["x"], np.arange(10.0))
