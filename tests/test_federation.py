"""Tests: cross-process observability federation (ISSUE 20) — mergeable
quantile sketches, the Federator's scrape/merge/re-export semantics
(counter reset-correction across worker restarts, label collisions,
partial scrapes with a dead worker, parse→merge→render→parse round
trips, the cluster SLO feed), the gateway wiring (federated /metrics,
?scope=cluster debug fan-out, stitched traces, /healthz federation
block), and a REAL `multiprocessing` subprocess worker federated via
`FederationConfig.extra_targets`."""

import http.client
import json
import multiprocessing
import os
import re
import time

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame, DataType
from mmlspark_tpu.obs.federation import (
    FederationConfig,
    Federator,
    identity_key,
    proc_identity,
    scrape_payload,
)
from mmlspark_tpu.obs.metrics import (
    MetricsRegistry,
    QuantileSketch,
    parse_prometheus,
)
from mmlspark_tpu.serving import (
    DistributedServingServer,
    FabricConfig,
    ServingServer,
    make_reply,
    parse_request,
)

# -- helpers ------------------------------------------------------------------


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _ident(label, pid, start=1000.0):
    return {"proc": label, "pid": pid, "start_time": start}


def _json_target(state):
    """Fetch callable serving `state` as a federation JSON payload; tests
    mutate the dict between scrapes to simulate progress and restarts."""

    def fetch(path):
        if state.get("dead"):
            raise ConnectionRefusedError("worker gone")
        payload = {
            "proc_identity": state["identity"],
            "exposition": state["exposition"],
            "sketches": state.get("sketches", {}),
        }
        return 200, json.dumps(payload).encode("utf-8")

    return fetch


def _counter_expo(name, value, labels='code="200"'):
    return (
        f"# TYPE {name} counter\n"
        f"{name}{{{labels}}} {value}\n"
    )


def _mk_fed(interval=1.0, clock=None, **kw):
    reg = MetricsRegistry()
    cfg = FederationConfig(scrape_interval_s=interval)
    fed = Federator(
        reg=reg, config=cfg, clock=clock or FakeClock(),
        gateway_label="fed-test", **kw
    )
    return reg, fed


def _echo_factory():
    def factory():
        def handler(df: DataFrame) -> DataFrame:
            parsed = parse_request(df, {"x": None})
            vals = np.asarray([float(v) * 2.0 for v in parsed["x"]])
            return make_reply(
                parsed.with_column("y", vals, DataType.DOUBLE), "y"
            )

        return handler

    return factory


def _post(port, api, payload, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(
        "POST", f"/{api}", body=json.dumps(payload),
        headers={"Content-Type": "application/json"},
    )
    r = conn.getresponse()
    body = r.read()
    conn.close()
    return r.status, body


def _get(port, route, timeout=10):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("GET", route)
    r = conn.getresponse()
    body = r.read()
    conn.close()
    return r.status, body


# -- QuantileSketch merge / serde ---------------------------------------------


class TestSketchMerge:
    def test_merge_matches_single_sketch_error_bound(self):
        # 5000 values split across two sketches: merged quantiles must
        # track true ranks about as well as one sketch over the union —
        # merge adds no error beyond the compactions it triggers
        rng = np.random.default_rng(7)
        vals = rng.normal(size=5000)
        a, b = QuantileSketch(k=128), QuantileSketch(k=128)
        for v in vals[:2500]:
            a.add(float(v))
        for v in vals[2500:]:
            b.add(float(v))
        a.merge(b)
        assert a.count == 5000
        srt = np.sort(vals)
        for q in (0.1, 0.5, 0.9, 0.99):
            est = a.quantile(q)
            rank = np.searchsorted(srt, est) / len(srt)
            assert abs(rank - q) < 0.05, f"q={q}: rank {rank}"

    def test_merge_is_count_and_range_exact(self):
        a, b = QuantileSketch(k=32), QuantileSketch(k=32)
        for v in range(100):
            a.add(float(v))
        for v in range(100, 300):
            b.add(float(v))
        a.merge(b)
        assert a.count == 300
        assert a.quantile(0.0) == 0.0
        assert a.quantile(1.0) == 299.0

    def test_merge_empty_is_identity(self):
        a, b = QuantileSketch(k=32), QuantileSketch(k=32)
        for v in range(50):
            a.add(float(v))
        before = a.quantiles((0.5, 0.9))
        a.merge(b)
        assert a.count == 50 and a.quantiles((0.5, 0.9)) == before
        b.merge(a)
        assert b.count == 50

    def test_serde_round_trip(self):
        a = QuantileSketch(k=64)
        for v in range(1000):
            a.add(float(v))
        d = json.loads(json.dumps(a.to_dict()))  # through real JSON
        back = QuantileSketch.from_dict(d)
        assert back.count == a.count
        for q in (0.01, 0.5, 0.99):
            assert back.quantile(q) == a.quantile(q)
        assert back.to_dict() == a.to_dict()


# -- process identity ---------------------------------------------------------


class TestProcIdentity:
    def test_identity_shape_and_key(self):
        ident = proc_identity()
        assert ident["pid"] == os.getpid()
        assert ident["proc"]
        assert identity_key(ident) == (os.getpid(), ident["start_time"])
        assert identity_key(None) is None
        assert identity_key({"pid": 1}) is None

    def test_scrape_payload_carries_identity_and_sketches(self):
        reg = MetricsRegistry()
        h = reg.histogram("fedid_ms", "h", ("k",))
        h.labels(k="a").observe(2.0)
        payload = scrape_payload(reg)
        assert payload["proc_identity"]["pid"] == os.getpid()
        assert "fedid_ms" in payload["sketches"]
        assert ("fedid_ms_count", (("k", "a"),)) in parse_prometheus(
            payload["exposition"]
        )

    def test_probe_payload_is_identity_only(self):
        reg = MetricsRegistry()
        reg.histogram("fedid_probe_ms", "h", ("k",)).labels(
            k="a"
        ).observe(1.0)
        payload = scrape_payload(reg, probe=True)
        assert payload["proc_identity"]["pid"] == os.getpid()
        assert payload["probe"] is True
        assert "exposition" not in payload
        assert "sketches" not in payload

    def test_same_process_target_downgrades_to_probe(self):
        # once a target is known to share this process, subsequent
        # scrapes ask for the identity-only probe (the full exposition
        # would be dropped by the identity dedupe anyway) and the target
        # still counts as live
        reg, fed = _mk_fed()
        paths = []

        def fetch(path):
            paths.append(path)
            probe = "probe=1" in path
            return 200, json.dumps(
                scrape_payload(reg, probe=probe)
            ).encode("utf-8")

        fed.set_targets({"self-peer": fetch})
        assert fed.scrape_target("self-peer")
        assert fed.scrape_target("self-peer")
        assert paths == [
            "/metrics?sketches=1",
            "/metrics?sketches=1&probe=1",
        ]
        snap = fed.snapshot()["targets"]["self-peer"]
        assert snap["scrapes_ok"] == 2
        assert not fed.is_stale("self-peer")

    def test_flight_and_memory_payloads_are_stamped(self):
        from mmlspark_tpu.obs.memory import memory_ledger
        from mmlspark_tpu.obs.profiler import device_profiler

        for payload in (
            device_profiler().flight(),
            memory_ledger().debug_payload(),
        ):
            ident = payload["proc_identity"]
            assert ident["pid"] == os.getpid()
            assert identity_key(ident) is not None


# -- Federator merge semantics ------------------------------------------------


class TestFederatorMerge:
    def test_counters_sum_into_cluster_series(self):
        reg, fed = _mk_fed()
        w1 = {"identity": _ident("w1", 111),
              "exposition": _counter_expo("fedm_requests_total", 3)}
        w2 = {"identity": _ident("w2", 222),
              "exposition": _counter_expo("fedm_requests_total", 4)}
        gw = reg.counter("fedm_requests_total", "t", ("code",))
        gw.labels(code="200").inc(5)
        fed.set_targets({"w1": _json_target(w1), "w2": _json_target(w2)})
        assert fed.scrape_all(force=True) == 2
        s = parse_prometheus(fed.render_text())
        key = lambda proc: (
            "fedm_requests_total",
            (("code", "200"), ("proc", proc)),
        )
        assert s[key("gateway")] == 5.0
        assert s[key("w1")] == 3.0
        assert s[key("w2")] == 4.0
        assert s[key("cluster")] == 12.0

    def test_counter_monotonic_across_worker_restart(self):
        _reg, fed = _mk_fed()
        w = {"identity": _ident("w", 111, start=1000.0),
             "exposition": _counter_expo("fedr_total", 10)}
        fed.set_targets({"w": _json_target(w)})
        fed.scrape_all(force=True)
        # restart: new incarnation (same label, new pid/start), counter
        # back near zero — the re-export must NOT go backwards
        w["identity"] = _ident("w", 112, start=2000.0)
        w["exposition"] = _counter_expo("fedr_total", 2)
        fed.scrape_all(force=True)
        s = parse_prometheus(fed.render_text())
        k = ("fedr_total", (("code", "200"), ("proc", "cluster")))
        assert s[k] == 12.0
        # and keeps counting from there
        w["exposition"] = _counter_expo("fedr_total", 5)
        fed.scrape_all(force=True)
        s = parse_prometheus(fed.render_text())
        assert s[k] == 15.0

    def test_counter_value_drop_without_identity_change_is_reset(self):
        _reg, fed = _mk_fed()
        w = {"identity": _ident("w", 111),
             "exposition": _counter_expo("fedd_total", 9)}
        fed.set_targets({"w": _json_target(w)})
        fed.scrape_all(force=True)
        w["exposition"] = _counter_expo("fedd_total", 1)
        fed.scrape_all(force=True)
        s = parse_prometheus(fed.render_text())
        assert s[("fedd_total", (("code", "200"), ("proc", "cluster")))] == 10.0

    def test_existing_proc_label_is_not_clobbered(self):
        # label-collision edge case: a worker series already carrying a
        # `proc` label passes through untouched (no double label, no
        # overwrite), and gauges never get a cluster aggregate
        _reg, fed = _mk_fed()
        w = {"identity": _ident("w", 111), "exposition": (
            "# TYPE fedc_gauge gauge\n"
            'fedc_gauge{proc="imposter"} 7\n'
        )}
        fed.set_targets({"w": _json_target(w)})
        fed.scrape_all(force=True)
        text = fed.render_text()
        s = parse_prometheus(text)
        assert s[("fedc_gauge", (("proc", "imposter"),))] == 7.0
        assert ("fedc_gauge", (("proc", "cluster"),)) not in s
        assert text.count('proc="imposter"') == 1

    def test_same_family_same_labels_across_procs_stay_distinct(self):
        _reg, fed = _mk_fed()
        w1 = {"identity": _ident("w1", 111),
              "exposition": _counter_expo("fedx_total", 1)}
        w2 = {"identity": _ident("w2", 222),
              "exposition": _counter_expo("fedx_total", 2)}
        fed.set_targets({"w1": _json_target(w1), "w2": _json_target(w2)})
        fed.scrape_all(force=True)
        text = fed.render_text()
        # identical (family, labels) from two procs must not collide: the
        # proc label keeps every line a distinct series after re-parse
        assert len(parse_prometheus(text)) == len(
            [l for l in text.splitlines() if l and not l.startswith("#")]
        )

    def test_identity_dedupe_collapses_same_process_sources(self):
        _reg, fed = _mk_fed()
        shared = _ident("w", 111)
        w1 = {"identity": shared,
              "exposition": _counter_expo("fedu_total", 6)}
        w2 = {"identity": shared,
              "exposition": _counter_expo("fedu_total", 6)}
        fed.set_targets({"w1": _json_target(w1), "w2": _json_target(w2)})
        fed.scrape_all(force=True)
        srcs = fed.sources()
        assert len(srcs) == 2  # local + ONE logical worker
        s = parse_prometheus(fed.render_text())
        assert s[("fedu_total", (("code", "200"), ("proc", "cluster")))] == 6.0

    def test_cluster_summary_quantiles_from_merged_sketches(self):
        reg, fed = _mk_fed()
        gw = reg.histogram("fedq_ms", "lat", ("engine",),
                           quantiles=(0.5, 0.99))
        for v in range(100, 200):
            gw.labels(engine="e").observe(float(v))
        wreg = MetricsRegistry()
        wh = wreg.histogram("fedq_ms", "lat", ("engine",),
                            quantiles=(0.5, 0.99))
        for v in range(100):
            wh.labels(engine="e").observe(float(v))
        w = {"identity": _ident("w", 111),
             "exposition": wreg.render_prometheus(),
             "sketches": wreg.export_sketches()}
        fed.set_targets({"w": _json_target(w)})
        fed.scrape_all(force=True)
        s = parse_prometheus(fed.render_text())
        base = (("engine", "e"), ("proc", "cluster"))
        assert s[("fedq_ms_count", base)] == 200.0
        assert s[("fedq_ms_sum", base)] == float(sum(range(200)))
        med = s[("fedq_ms", base + (("quantile", "0.5"),))]
        # honest cluster median over the union 0..199, not either proc's
        assert 80.0 <= med <= 120.0

    def test_render_parses_and_round_trips(self):
        reg, fed = _mk_fed()
        reg.counter("fedt_total", "t", ("code",)).labels(code="200").inc(2)
        h = reg.histogram("fedt_ms", "lat", ("engine",))
        h.labels(engine="e").observe(3.0)
        w = {"identity": _ident("w", 111),
             "exposition": _counter_expo("fedt_total", 8)}
        fed.set_targets({"w": _json_target(w)})
        fed.scrape_all(force=True)
        text1 = fed.render_text()
        s1 = parse_prometheus(text1)  # the whole render must parse
        # deterministic: render → parse → render is a fixed point
        assert parse_prometheus(fed.render_text()) == s1
        # hierarchical: a second federator scraping this one's exposition
        # preserves every per-proc series verbatim after its own render
        _reg2, fed2 = _mk_fed()
        parent = {"identity": _ident("gw1", 999),
                  "exposition": text1}
        fed2.set_targets({"gw1": _json_target(parent)})
        fed2.scrape_all(force=True)
        s2 = parse_prometheus(fed2.render_text())
        for (name, labels), v in s1.items():
            procs = dict(labels)
            if procs.get("proc") in ("gateway", "w"):
                assert s2[(name, labels)] == v, (name, labels)


# -- Federator failure / staleness telemetry ----------------------------------


class TestFederatorFailures:
    def test_dead_worker_partial_scrape_and_staleness(self):
        clk = FakeClock()
        reg, fed = _mk_fed(interval=1.0, clock=clk)
        w1 = {"identity": _ident("w1", 111),
              "exposition": _counter_expo("fedf_total", 3)}
        w2 = {"identity": _ident("w2", 222),
              "exposition": _counter_expo("fedf_total", 4)}
        fed.set_targets({"w1": _json_target(w1), "w2": _json_target(w2)})
        fed.scrape_all(force=True)
        assert not fed.is_stale("w2")
        # w2 dies; scrapes keep succeeding for w1, failing for w2
        w2["dead"] = True
        clk.advance(1.1)
        fed.scrape_all()
        snap = fed.snapshot()["targets"]
        assert snap["w1"]["scrapes_ok"] == 2
        assert snap["w2"]["scrapes_failed"] == 1
        assert "ConnectionRefused" in snap["w2"]["last_error"]
        # failure counter by kind, on the gateway registry
        s = parse_prometheus(reg.render_prometheus())
        assert s[(
            "obs_federation_scrape_failures_total",
            (("gateway", "fed-test"), ("kind", "transport"),
             ("worker", "w2")),
        )] == 1.0
        # staleness rises past the budget; w1 stays fresh
        clk.advance(3.0)
        assert fed.staleness_s("w2") > 3.0
        assert fed.is_stale("w2") and not fed.is_stale("w1")
        stale_v = s_after = parse_prometheus(reg.render_prometheus())[(
            "obs_federation_staleness_seconds",
            (("gateway", "fed-test"), ("worker", "w2")),
        )]
        assert stale_v > 3.0
        # last-good state keeps rendering while dead (explicit, not blank)
        sf = parse_prometheus(fed.render_text())
        assert sf[("fedf_total", (("code", "200"), ("proc", "w2")))] == 4.0

    def test_new_target_has_grace_not_instant_staleness(self):
        clk = FakeClock()
        _reg, fed = _mk_fed(interval=1.0, clock=clk)
        fed.set_targets({"w": _json_target(
            {"identity": _ident("w", 1),
             "exposition": _counter_expo("g_total", 1)}
        )})
        assert fed.staleness_s("w") == 0.0 and not fed.is_stale("w")
        clk.advance(3.5)  # never scraped: NOW it is stale
        assert fed.is_stale("w")

    def test_http_and_parse_failure_kinds(self):
        reg, fed = _mk_fed()
        fed.set_targets({
            "w5xx": lambda path: (500, b"boom"),
            "wbad": lambda path: (200, b'{"exposition": 3}'),
        })
        fed.scrape_all(force=True)
        s = parse_prometheus(reg.render_prometheus())
        base = (("gateway", "fed-test"),)
        assert s[("obs_federation_scrape_failures_total",
                  base + (("kind", "http"), ("worker", "w5xx")))] == 1.0
        assert s[("obs_federation_scrape_failures_total",
                  base + (("kind", "parse"), ("worker", "wbad")))] == 1.0

    def test_fanout_debug_partial_results(self):
        _reg, fed = _mk_fed()

        def good(path):
            return 200, json.dumps({
                "proc_identity": _ident("w1", 111), "depth": 4,
            }).encode()

        def dead(path):
            raise ConnectionRefusedError("gone")

        fed.set_targets({"w1": good, "w2": dead})
        out = fed.fanout_debug(
            "/debug/flight", {"proc_identity": proc_identity(), "depth": 1}
        )
        assert out["scope"] == "cluster"
        assert out["procs"]["gateway"]["depth"] == 1
        assert out["procs"]["w1"]["depth"] == 4
        assert out["errors"] == [
            {"worker": 1, "error": out["errors"][0]["error"]}
        ]
        assert "ConnectionRefused" in out["errors"][0]["error"]

    def test_close_removes_staleness_children(self):
        reg, fed = _mk_fed()
        fed.set_targets({"w": _json_target(
            {"identity": _ident("w", 1),
             "exposition": _counter_expo("c_total", 1)}
        )})
        assert 'worker="w"' in reg.render_prometheus()
        fed.close()
        assert (
            "obs_federation_staleness_seconds{"
            not in reg.render_prometheus()
        )


# -- cluster SLO feed ---------------------------------------------------------


class _FakeSLO:
    def __init__(self):
        self.calls = []

    def observe_batch(self, engine, code, latency_ms, n):
        self.calls.append((engine, code, latency_ms, n))


def _slo_expo(count, total, engine="w0", code="200"):
    lab = f'engine="{engine}",code="{code}"'
    return (
        "# TYPE serving_request_latency_ms summary\n"
        f"serving_request_latency_ms_count{{{lab}}} {count}\n"
        f"serving_request_latency_ms_sum{{{lab}}} {total}\n"
    )


class TestClusterSLOFeed:
    def test_deltas_replayed_under_cluster_engine(self):
        slo = _FakeSLO()
        reg = MetricsRegistry()
        fed = Federator(
            reg=reg, config=FederationConfig(scrape_interval_s=1.0),
            clock=FakeClock(), slo=slo, slo_engine="clu",
            gateway_label="fed-slo",
        )
        w = {"identity": _ident("w0", 111), "exposition": _slo_expo(10, 50)}
        fed.set_targets({"w0": _json_target(w)})
        fed.scrape_all(force=True)
        assert slo.calls == []  # first sight primes, never replays history
        w["exposition"] = _slo_expo(14, 70)
        fed.scrape_all(force=True)
        assert slo.calls == [("clu", 200, 5.0, 4)]  # (70-50)/4 ms each

    def test_new_series_from_baselined_source_replays_fully(self):
        # the bench-caught bug: an error burst creates a code="500"
        # series the scraper has never seen — per-SOURCE priming must
        # not swallow it as "history"; its whole count replays
        slo = _FakeSLO()
        reg = MetricsRegistry()
        fed = Federator(
            reg=reg, config=FederationConfig(scrape_interval_s=1.0),
            clock=FakeClock(), slo=slo, slo_engine="clu",
            gateway_label="fed-slo3",
        )
        w = {"identity": _ident("w0", 111), "exposition": _slo_expo(10, 50)}
        fed.set_targets({"w0": _json_target(w)})
        fed.scrape_all(force=True)
        assert slo.calls == []
        w["exposition"] = _slo_expo(10, 50) + _slo_expo(
            24, 240, code="500")
        fed.scrape_all(force=True)
        assert slo.calls == [("clu", 500, 10.0, 24)]

    def test_excluded_engine_and_burst_cap(self):
        slo = _FakeSLO()
        reg = MetricsRegistry()
        fed = Federator(
            reg=reg,
            config=FederationConfig(
                scrape_interval_s=1.0, slo_max_events_per_scrape=3
            ),
            clock=FakeClock(), slo=slo, slo_engine="clu",
            slo_exclude_engines=("edge",), gateway_label="fed-slo2",
        )
        w = {"identity": _ident("w0", 111),
             "exposition": _slo_expo(0, 0) + _slo_expo(
                 5, 10, engine="edge", code="500")}
        fed.set_targets({"w0": _json_target(w)})
        fed.scrape_all(force=True)
        w["exposition"] = _slo_expo(100, 400) + _slo_expo(
            9, 20, engine="edge", code="500")
        fed.scrape_all(force=True)
        # excluded engine never replayed; big delta capped at 3 events
        assert slo.calls == [("clu", 200, 4.0, 3)]


# -- gateway integration (in-process workers) ---------------------------------


FAST = dict(
    failure_threshold=2, open_secs=0.2, backoff_base_ms=1.0,
    backoff_max_ms=5.0, health_interval_s=0.05,
)


class TestGatewayFederation:
    def test_gateway_federates_metrics_debug_and_healthz(self):
        srv = DistributedServingServer(
            _echo_factory(), n_workers=2, api_name="fedgw", port=0,
            fabric=FabricConfig(**FAST),
            federation=FederationConfig(scrape_interval_s=0.1),
        )
        srv.start()
        try:
            for _ in range(4):
                status, _ = _post(srv.port, "fedgw", {"x": 1.0})
                assert status == 200
            time.sleep(0.3)  # let a scrape round land
            status, body = _get(srv.port, "/metrics")
            assert status == 200
            text = body.decode()
            assert 'proc="gateway"' in text and 'proc="cluster"' in text
            s = parse_prometheus(text)
            cluster_counts = {
                k: v for k, v in s.items()
                if k[0] == "serving_request_latency_ms_count"
                and ("proc", "cluster") in k[1]
            }
            assert cluster_counts
            # /healthz: federation block + cluster SLO view
            status, body = _get(srv.port, "/healthz")
            hz = json.loads(body)
            fedblk = hz["federation"]
            assert set(fedblk["targets"]) == {"worker-0", "worker-1"}
            assert all(
                t["scrapes_ok"] >= 1 for t in fedblk["targets"].values()
            )
            assert fedblk["slo_engine"] == srv.cluster_engine
            assert "cluster_slos" in hz
            # router view carries scrape-staleness annotations
            workers = hz["router"]["workers"]
            assert all("scrape_stale" in w for w in workers)
            assert all(not w["scrape_stale"] for w in workers)
            # ?scope=cluster debug fan-out (in-process workers share the
            # gateway's identity, so they dedupe into one proc entry)
            status, body = _get(srv.port, "/debug/memory?scope=cluster")
            mem = json.loads(body)
            assert mem["scope"] == "cluster" and mem["errors"] == []
            assert "gateway" in mem["procs"]
            gw_mem = mem["procs"]["gateway"]
            assert gw_mem["proc_identity"]["pid"] == os.getpid()
            status, body = _get(srv.port, "/debug/flight?scope=cluster")
            fl = json.loads(body)
            assert fl["scope"] == "cluster"
            # stitched trace: pick a real trace id off the local ring
            from mmlspark_tpu.obs.tracing import tracer

            tid = next(
                sp.trace_id for sp in tracer().spans() if sp.name == "http"
            )
            status, body = _get(
                srv.port, f"/debug/trace?trace_id={tid}&scope=cluster"
            )
            tree = json.loads(body)
            assert tree["scope"] == "cluster"
            assert tree["trace_id"] == tid and tree["span_count"] >= 1
            # federated JSON payload for hierarchical federation
            status, body = _get(srv.port, "/metrics?sketches=1")
            pj = json.loads(body)
            assert pj["proc_identity"]["pid"] == os.getpid()
            assert "serving_request_latency_ms" in pj["sketches"]
        finally:
            srv.stop()

    def test_federation_disabled_keeps_plain_exposition(self):
        srv = DistributedServingServer(
            _echo_factory(), n_workers=1, api_name="fedoff", port=0,
            fabric=FabricConfig(**FAST),
            federation=FederationConfig(enabled=False),
        )
        srv.start()
        try:
            assert srv.federator is None
            status, body = _get(srv.port, "/metrics")
            assert status == 200
            text = body.decode()
            assert 'proc="cluster"' not in text
            parse_prometheus(text)
            status, body = _get(srv.port, "/healthz")
            assert json.loads(body)["federation"] is None
        finally:
            srv.stop()


# -- real subprocess worker ---------------------------------------------------


def _subprocess_obs_worker(port_q, stop_q):
    """Spawn-target: a real OS-process peer running its own ServingServer
    with its own (empty-until-now) obs singletons. Serves one request to
    itself so its registry and trace ring hold distinguishable state, then
    parks until the parent signals."""
    from mmlspark_tpu.obs.federation import set_proc_label
    from mmlspark_tpu.obs.tracing import tracer

    set_proc_label("subw-proc")

    def handler(df):
        parsed = parse_request(df, {"x": None})
        vals = np.asarray([float(v) * 2.0 for v in parsed["x"]])
        return make_reply(
            parsed.with_column("y", vals, DataType.DOUBLE), "y"
        )

    srv = ServingServer(handler, api_name="subw", port=0)
    srv.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        conn.request(
            "POST", "/subw", body=json.dumps({"x": 2.0}),
            headers={"Content-Type": "application/json"},
        )
        conn.getresponse().read()
        conn.close()
        tid = next(
            sp.trace_id for sp in tracer().spans() if sp.name == "http"
        )
        port_q.put((srv.port, tid))
        stop_q.get(timeout=120)
    finally:
        srv.stop()


class TestSubprocessFederation:
    def test_gateway_federates_a_real_subprocess_worker(self):
        ctx = multiprocessing.get_context("spawn")
        port_q, stop_q = ctx.Queue(), ctx.Queue()
        proc = ctx.Process(
            target=_subprocess_obs_worker, args=(port_q, stop_q),
            daemon=True,
        )
        proc.start()
        srv = None
        try:
            wport, wtid = port_q.get(timeout=120)
            srv = DistributedServingServer(
                _echo_factory(), n_workers=1, api_name="fedsub", port=0,
                fabric=FabricConfig(**FAST),
                federation=FederationConfig(
                    scrape_interval_s=0.2,
                    extra_targets=(("127.0.0.1", wport),),
                ),
            )
            srv.start()
            status, body = _get(srv.port, "/metrics", timeout=30)
            assert status == 200
            text = body.decode()
            # the subprocess's serving series federate under its own proc
            # label — a DIFFERENT process's registry, not ours
            sub_series = re.findall(
                r'serving_request_latency_ms_count\{[^}]*'
                r'engine="subw-[^"]*"[^}]*\}', text
            )
            assert sub_series, text[:2000]
            assert any('proc="extra-0"' in line for line in sub_series)
            # its identity (pid != ours) shows in the federation snapshot
            status, body = _get(srv.port, "/healthz", timeout=30)
            ident = json.loads(body)["federation"]["targets"]["extra-0"][
                "proc_identity"
            ]
            assert ident["proc"] == "subw-proc"
            assert ident["pid"] != os.getpid()
            # stitched cluster trace includes the subprocess's spans for a
            # trace the gateway never saw locally
            status, body = _get(
                srv.port,
                f"/debug/trace?trace_id={wtid}&scope=cluster", timeout=30,
            )
            tree = json.loads(body)
            assert tree["scope"] == "cluster" and tree["errors"] == []
            assert tree["trace_id"] == wtid
            assert tree["span_count"] >= 1
            names = set()

            def walk(nodes):
                for n in nodes:
                    names.add(n["name"])
                    walk(n.get("children", ()))

            walk(tree["roots"])
            assert "http" in names
            # cluster-scope memory view carries the subprocess entry too
            status, body = _get(
                srv.port, "/debug/memory?scope=cluster", timeout=30
            )
            mem = json.loads(body)
            assert "extra-0" in mem["procs"]
            assert mem["procs"]["extra-0"]["proc_identity"]["pid"] == (
                ident["pid"]
            )
        finally:
            if srv is not None:
                srv.stop()
            stop_q.put(None)
            proc.join(timeout=30)
            if proc.is_alive():
                proc.terminate()
