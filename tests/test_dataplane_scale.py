"""Data-plane scale smoke: the relational ops and SAR must carry
reference-scale workloads (round-3 verdict item 7 — millions of rows feeding
SAR/stats were previously pure-Python loops)."""

import time

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, DataType
from mmlspark_tpu.recommendation.sar import SAR, _is_sparse

N = 1_000_000


def test_join_1m_rows():
    rng = np.random.default_rng(0)
    left = DataFrame.from_dict(
        {"k": rng.integers(0, 200_000, N).astype(np.int64), "a": rng.normal(size=N)}
    )
    right = DataFrame.from_dict(
        {
            "k": np.arange(200_000, dtype=np.int64),
            "b": np.arange(200_000, dtype=np.float64),
        }
    )
    t0 = time.perf_counter()
    out = left.join(right, on="k", how="inner")
    dt = time.perf_counter() - t0
    assert len(out) == N
    np.testing.assert_allclose(out["b"], out["k"].astype(np.float64))
    # vectorized path is ~1s; the old dict loop took tens of seconds
    assert dt < 90, f"join too slow: {dt:.1f}s"  # loop impl took minutes


def test_group_by_1m_rows():
    rng = np.random.default_rng(1)
    df = DataFrame.from_dict(
        {
            "k": rng.integers(0, 50_000, N).astype(np.int64),
            "v": np.ones(N, np.float64),
        }
    )
    t0 = time.perf_counter()
    agg = df.group_by("k").agg(total=("v", "sum"))
    dt = time.perf_counter() - t0
    assert len(agg) == 50_000
    np.testing.assert_allclose(np.sort(agg["k"]), np.arange(50_000))
    assert agg["total"].sum() == N
    assert dt < 90, f"group_by too slow: {dt:.1f}s"


def test_join_semantics_match_small():
    """Vectorized join must reproduce the documented layout on a case with
    duplicates, misses on both sides, and multi-key."""
    left = DataFrame.from_dict(
        {
            "k": np.array([1, 2, 2, 3, 5], np.int64),
            "g": np.array(["x", "x", "y", "x", "x"], object),
            "a": np.arange(5.0),
        },
        types={"g": DataType.STRING},
    )
    right = DataFrame.from_dict(
        {
            "k": np.array([2, 2, 3, 4], np.int64),
            "g": np.array(["x", "x", "x", "x"], object),
            "b": np.arange(4.0) * 10,
        },
        types={"g": DataType.STRING},
    )
    inner = left.join(right, on=["k", "g"], how="inner")
    # left row 1 (k=2,g=x) matches right rows 0,1; left row 3 (k=3,g=x)
    # matches right row 2
    np.testing.assert_array_equal(inner["a"], [1.0, 1.0, 3.0])
    np.testing.assert_array_equal(inner["b"], [0.0, 10.0, 20.0])

    louter = left.join(right, on=["k", "g"], how="left")
    assert len(louter) == 6  # 3 matches + 3 unmatched left rows inline
    np.testing.assert_array_equal(louter["a"], [0.0, 1.0, 1.0, 2.0, 3.0, 4.0])

    full = left.join(right, on=["k", "g"], how="outer")
    assert len(full) == 7  # + unmatched right row (k=4)
    assert full["k"][-1] == 4


def test_sar_sparse_mode_matches_dense():
    """Above _DENSE_LIMIT SAR goes sparse; results must match the dense
    path exactly."""
    rng = np.random.default_rng(2)
    n_events = 5000
    df = DataFrame.from_dict(
        {
            "user_idx": rng.integers(0, 300, n_events).astype(np.float64),
            "item_idx": rng.integers(0, 40, n_events).astype(np.float64),
            "rating": rng.integers(1, 5, n_events).astype(np.float64),
        }
    )
    dense_model = SAR(support_threshold=1).fit(df)

    old = SAR._DENSE_LIMIT
    SAR._DENSE_LIMIT = 1  # force sparse
    try:
        sparse_model = SAR(support_threshold=1).fit(df)
    finally:
        SAR._DENSE_LIMIT = old

    assert _is_sparse(sparse_model.get(sparse_model.user_affinity))
    np.testing.assert_allclose(
        dense_model.get_item_similarity(),
        sparse_model.get_item_similarity(),
        rtol=1e-6, atol=1e-6,
    )
    scores_d = dense_model.transform(df)["prediction"]
    scores_s = sparse_model.transform(df)["prediction"]
    np.testing.assert_allclose(scores_d, scores_s, rtol=1e-4, atol=1e-4)

    rd = dense_model.recommend_for_all_users(5)
    rs = sparse_model.recommend_for_all_users(5)
    assert list(rd["recommendations"][0]) == list(rs["recommendations"][0])


def test_sar_100k_users_sparse_fit():
    """Reference-scale shape: 100k users x 10k items would be 4 GB dense;
    sparse fit + blocked recommend must handle it in bounded memory."""
    rng = np.random.default_rng(3)
    n_events = 200_000
    df = DataFrame.from_dict(
        {
            "user_idx": rng.integers(0, 100_000, n_events).astype(np.float64),
            "item_idx": rng.integers(0, 10_000, n_events).astype(np.float64),
            "rating": np.ones(n_events),
        }
    )
    t0 = time.perf_counter()
    model = SAR(support_threshold=1).fit(df)
    dt = time.perf_counter() - t0
    assert _is_sparse(model.get(model.user_affinity))
    assert dt < 180, f"sparse SAR fit too slow: {dt:.1f}s"  # CI runs suites concurrently
    # blocked scoring of a subset
    sub = DataFrame.from_dict(
        {
            "user_idx": df["user_idx"][:1000],
            "item_idx": df["item_idx"][:1000],
        }
    )
    pred = model.transform(sub)["prediction"]
    assert np.isfinite(pred).all() and (pred >= 0).all()
