"""Tests: crash-consistent checkpoint/resume subsystem (ISSUE 8).

Three layers, matching the acceptance criteria:

- **Store fault matrix** — for every injected storage fault (torn write,
  crash before/after rename, bit flip, ENOSPC, crash at *every* fs op via
  the recorded-op sweep) the verified load never returns a corrupt
  artifact: it falls back to the last good generation and increments
  ``checkpoint_resume_total{outcome="fallback"}``.
- **Persisting-class crash sweeps** — stage dirs, network bundles and
  boosters interrupted at injected fault points reload as either the new
  or the previous version, never a torn hybrid.
- **Kill-and-resume parity** — a `TPULearner` fit killed at any checkpoint
  boundary and resumed reaches the uninterrupted fit's loss trajectory
  (exact on the same backend); a GBDT fit killed mid-boosting resumes to
  bit-identical ensemble predictions, bagging/feature-fraction rng
  sequences included.
"""

import glob
import json
import os

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.io.checkpoint import (
    CheckpointStore,
    CorruptArtifactError,
    pack_arrays,
    unpack_arrays,
)
from mmlspark_tpu.io.storage_faults import (
    InjectedCrash,
    StorageFaultInjector,
    installed,
)
from mmlspark_tpu.obs.metrics import registry


def _payload(tag: bytes):
    return {
        "weights.npz": pack_arrays({"w": np.arange(32, dtype=np.float32)}),
        "meta.json": b'{"tag": "' + tag + b'"}',
    }


def _fallbacks() -> float:
    fam = registry().counter("checkpoint_resume_total",
                             "Checkpoint load outcomes", ("outcome",))
    return fam.labels(outcome="fallback").value()


# -- store basics --------------------------------------------------------------


def test_store_roundtrip_generations_and_retention(tmp_path):
    st = CheckpointStore(str(tmp_path), keep_last=2)
    assert st.load_latest() is None
    g1 = st.save(_payload(b"one"), meta={"epoch": 1})
    g2 = st.save(_payload(b"two"), meta={"epoch": 2})
    g3 = st.save(_payload(b"three"), meta={"epoch": 3})
    assert (g1, g2, g3) == (1, 2, 3)
    # retention: keep_last=2 pruned gen 1
    assert st.generations() == [2, 3]
    ck = st.load_latest()
    assert ck.generation == 3
    assert ck.meta["epoch"] == 3
    assert ck.json("meta.json")["tag"] == "three"
    np.testing.assert_array_equal(
        ck.arrays("weights.npz")["w"], np.arange(32, dtype=np.float32)
    )


def test_store_rejects_reserved_and_nested_names(tmp_path):
    st = CheckpointStore(str(tmp_path))
    with pytest.raises(ValueError):
        st.save({"MANIFEST.json": b"x"})
    with pytest.raises(ValueError):
        st.save({os.path.join("sub", "f.bin"): b"x"})
    with pytest.raises(ValueError):
        CheckpointStore(str(tmp_path), keep_last=0)


def test_store_gcs_stale_tmp_dirs(tmp_path):
    st = CheckpointStore(str(tmp_path))
    stale = tmp_path / ".tmp-deadbeef"
    stale.mkdir()
    (stale / "partial.bin").write_bytes(b"torn")
    st.save(_payload(b"one"))
    assert not stale.exists()  # reclaimed by the next writer
    assert st.load_latest().generation == 1


# -- store fault matrix --------------------------------------------------------


@pytest.mark.parametrize("target,at_byte", [
    ("weights.npz", 0), ("weights.npz", 7), ("meta.json", 3),
    ("MANIFEST.json", 0), ("MANIFEST.json", 11),
])
def test_torn_write_never_surfaces(tmp_path, target, at_byte):
    """A write torn at byte k (power cut mid-write) crashes the writer; the
    next load returns the previous generation — the torn bytes live only in
    an invisible tmp dir."""
    inj = StorageFaultInjector()
    st = CheckpointStore(str(tmp_path), fault_injector=inj)
    st.save(_payload(b"good"))
    inj.torn_write(target, at_byte=at_byte)
    with pytest.raises(InjectedCrash):
        st.save(_payload(b"doomed"))
    ck = st.load_latest()
    assert ck.generation == 1
    assert ck.json("meta.json")["tag"] == "good"


def test_crash_before_rename_keeps_previous(tmp_path):
    inj = StorageFaultInjector()
    st = CheckpointStore(str(tmp_path), fault_injector=inj)
    st.save(_payload(b"good"))
    inj.crash_before_rename()
    with pytest.raises(InjectedCrash):
        st.save(_payload(b"doomed"))
    assert st.generations() == [1]
    assert st.load_latest().json("meta.json")["tag"] == "good"


def test_crash_after_rename_commits_new(tmp_path):
    """The rename IS the commit point: a kill immediately after it must
    load the new generation (nothing after the rename is load-bearing)."""
    inj = StorageFaultInjector()
    st = CheckpointStore(str(tmp_path), fault_injector=inj)
    st.save(_payload(b"old"))
    inj.crash_after_rename()
    with pytest.raises(InjectedCrash):
        st.save(_payload(b"new"))
    ck = st.load_latest()
    assert ck.generation == 2
    assert ck.json("meta.json")["tag"] == "new"


def test_crash_on_fsync_falls_back(tmp_path):
    inj = StorageFaultInjector()
    st = CheckpointStore(str(tmp_path), fault_injector=inj)
    st.save(_payload(b"good"))
    inj.crash_on_fsync("weights.npz")
    with pytest.raises(InjectedCrash):
        st.save(_payload(b"doomed"))
    assert st.load_latest().generation == 1


def test_bit_flip_quarantines_and_falls_back(tmp_path):
    inj = StorageFaultInjector()
    st = CheckpointStore(str(tmp_path), fault_injector=inj)
    st.save(_payload(b"good"))
    st.save(_payload(b"flipped"))
    before = _fallbacks()
    StorageFaultInjector.bit_flip(
        os.path.join(st._gen_dir(2), "weights.npz")
    )
    ck = st.load_latest()
    assert ck.generation == 1
    assert ck.json("meta.json")["tag"] == "good"
    assert _fallbacks() == before + 1
    # the corrupt generation was quarantined, not deleted (forensics)
    q = glob.glob(os.path.join(str(tmp_path), "quarantine", "gen_*"))
    assert len(q) == 1 and "hash" in q[0]
    assert st.generations() == [1]


def test_truncated_file_and_manifest_fall_back(tmp_path):
    st = CheckpointStore(str(tmp_path))
    st.save(_payload(b"good"))
    st.save(_payload(b"torn"))
    StorageFaultInjector.truncate(
        os.path.join(st._gen_dir(2), "weights.npz"), 5
    )
    assert st.load_latest().generation == 1
    # now tear gen 1's manifest too: nothing loadable -> None, store empty
    StorageFaultInjector.truncate(
        os.path.join(st._gen_dir(1), "MANIFEST.json"), 7
    )
    assert st.load_latest() is None


def test_enospc_raises_and_store_stays_loadable(tmp_path):
    inj = StorageFaultInjector()
    st = CheckpointStore(str(tmp_path), fault_injector=inj)
    st.save(_payload(b"good"))
    inj.enospc("weights.npz")
    with pytest.raises(OSError) as e:
        st.save(_payload(b"doomed"))
    import errno

    assert e.value.errno == errno.ENOSPC
    # a LIVE failure cleans its scratch and the store still loads
    assert not glob.glob(os.path.join(str(tmp_path), ".tmp-*"))
    assert st.load_latest().json("meta.json")["tag"] == "good"


def test_slow_fsync_still_commits(tmp_path):
    inj = StorageFaultInjector()
    inj.slow_fsync(0.01)
    st = CheckpointStore(str(tmp_path), fault_injector=inj)
    st.save(_payload(b"slow"))
    assert st.load_latest().json("meta.json")["tag"] == "slow"


def test_crash_at_every_fs_op_sweep(tmp_path):
    """The exhaustive crash-point sweep: record every filesystem operation
    one commit performs, then kill a fresh commit at each of them in turn.
    After every kill the store loads EITHER the previous generation intact
    OR the new one intact — never a torn hybrid, never nothing."""
    rec = StorageFaultInjector()
    rec.record_ops = True
    probe = CheckpointStore(str(tmp_path / "probe"), fault_injector=rec)
    probe.save(_payload(b"one"))
    n_ops = len(rec.ops)
    assert n_ops >= 6  # 3 files x (write+fsync) at minimum

    old, new = _payload(b"old"), _payload(b"new")
    for op_idx in range(n_ops):
        root = tmp_path / f"sweep{op_idx}"
        st = CheckpointStore(str(root))
        st.save(old)
        inj = StorageFaultInjector()
        inj.crash_at_op(op_idx)
        st_f = CheckpointStore(str(root), fault_injector=inj)
        with pytest.raises(InjectedCrash):
            st_f.save(new)
        ck = CheckpointStore(str(root)).load_latest()
        assert ck is not None, f"nothing loadable after crash at op {op_idx}"
        want = old if ck.generation == 1 else new
        assert ck.files == {**want}, f"torn hybrid after crash at op {op_idx}"


# -- metrics + spans -----------------------------------------------------------


def test_checkpoint_metrics_and_spans(tmp_path):
    from mmlspark_tpu.obs import tracer

    st = CheckpointStore(str(tmp_path))
    st.save(_payload(b"m"))
    assert st.load_latest() is not None
    text = registry().render_prometheus()
    for family in ("checkpoint_write_seconds", "checkpoint_bytes_total",
                   "checkpoint_resume_total", "checkpoint_generation"):
        assert family in text, family
    names = {s.name for s in tracer().spans()}
    assert {"checkpoint:commit", "checkpoint:load"} <= names


# -- persisting-class crash sweeps ---------------------------------------------


def test_save_stage_crash_sweep(tmp_path):
    """save_stage interrupted around its publish: the previous stage save
    survives — at its path for pre-publish faults, at the parked trash
    sibling inside the swap window — and is never torn."""
    from mmlspark_tpu.core.serialize import load_stage, save_stage
    from mmlspark_tpu.stages.basic import SelectColumns

    path = str(tmp_path / "stage")
    save_stage(SelectColumns(cols=["v1"]), path)

    # fault 1: crash at a staged-file fsync — tmp is torn, final untouched
    inj = StorageFaultInjector()
    inj.crash_on_fsync("metadata.json")
    with pytest.raises(InjectedCrash):
        with installed(inj):
            save_stage(SelectColumns(cols=["v2"]), path, overwrite=True)
    assert load_stage(path).get("cols") == ["v1"]

    # fault 2: crash AFTER the publish rename — the new save is committed
    inj2 = StorageFaultInjector()
    inj2.crash_after_rename()
    with pytest.raises(InjectedCrash):
        with installed(inj2):
            save_stage(SelectColumns(cols=["v2"]), path, overwrite=True)
    assert load_stage(path).get("cols") == ["v2"]

    # fault 3: crash BEFORE the rename, inside the swap window — the
    # incumbent is parked at a trash sibling, recoverable, never deleted
    inj3 = StorageFaultInjector()
    inj3.crash_before_rename()
    with pytest.raises(InjectedCrash):
        with installed(inj3):
            save_stage(SelectColumns(cols=["v3"]), path, overwrite=True)
    if os.path.exists(path):
        assert load_stage(path).get("cols") in (["v2"], ["v3"])
    else:
        # exactly one park: publish_dir reclaims trash superseded by the
        # fault-2 commit before parking the current incumbent
        parked = glob.glob(path + ".trash-*")
        assert len(parked) == 1, parked
        assert load_stage(parked[0]).get("cols") == ["v2"]


def test_save_stage_fresh_crash_leaves_no_final_path(tmp_path):
    from mmlspark_tpu.core.serialize import save_stage
    from mmlspark_tpu.stages.basic import SelectColumns

    path = str(tmp_path / "fresh")
    inj = StorageFaultInjector()
    inj.crash_before_rename()
    with pytest.raises(InjectedCrash):
        with installed(inj):
            save_stage(SelectColumns(cols=["v1"]), path)
    assert not os.path.exists(path)  # no half-written stage dir


def test_network_bundle_crash_sweep(tmp_path):
    import jax

    from mmlspark_tpu.dnn import mlp
    from mmlspark_tpu.dnn.network import NetworkBundle

    net = mlp(4, [8], 2)
    v1 = net.init(jax.random.PRNGKey(0))
    v2 = net.init(jax.random.PRNGKey(1))
    path = str(tmp_path / "bundle")
    NetworkBundle(net, jax.device_get(v1)).save_to_dir(path)

    inj = StorageFaultInjector()
    inj.crash_on_fsync("variables.npz")
    with pytest.raises(InjectedCrash):
        with installed(inj):
            NetworkBundle(net, jax.device_get(v2)).save_to_dir(path)
    loaded = NetworkBundle.load_from_dir(path)
    np.testing.assert_array_equal(
        loaded.variables["params"]["dense_0"]["kernel"],
        np.asarray(v1["params"]["dense_0"]["kernel"]),
    )

    inj2 = StorageFaultInjector()
    inj2.crash_after_rename()
    with pytest.raises(InjectedCrash):
        with installed(inj2):
            NetworkBundle(net, jax.device_get(v2)).save_to_dir(path)
    loaded = NetworkBundle.load_from_dir(path)
    np.testing.assert_array_equal(
        loaded.variables["params"]["dense_0"]["kernel"],
        np.asarray(v2["params"]["dense_0"]["kernel"]),
    )


def test_booster_native_model_crash_sweep(tmp_path):
    from mmlspark_tpu.gbdt.booster import Booster
    from mmlspark_tpu.gbdt.objectives import make_objective
    from mmlspark_tpu.gbdt.trainer import TrainConfig, train_booster

    rng = np.random.default_rng(0)
    x = rng.normal(size=(120, 4))
    y = (x[:, 0] > 0).astype(np.float64)
    cfg = TrainConfig(num_iterations=3, num_leaves=7, verbosity=0)
    b1 = train_booster(x, y, make_objective("binary", num_class=2), cfg)
    cfg2 = TrainConfig(num_iterations=5, num_leaves=7, verbosity=0)
    b2 = train_booster(x, y, make_objective("binary", num_class=2), cfg2)

    path = str(tmp_path / "model.txt")
    b1.save_native_model(path)

    # torn write of the replacement: the old model file survives intact
    inj = StorageFaultInjector()
    inj.torn_write("model.txt", at_byte=64)
    with pytest.raises(InjectedCrash):
        with installed(inj):
            b2.save_native_model(path)
    np.testing.assert_array_equal(
        np.asarray(Booster.load_native_model(path).predict_raw(x)),
        np.asarray(b1.predict_raw(x)),
    )

    # crash after the rename: the new model is committed
    inj2 = StorageFaultInjector()
    inj2.crash_after_rename()
    with pytest.raises(InjectedCrash):
        with installed(inj2):
            b2.save_native_model(path)
    np.testing.assert_array_equal(
        np.asarray(Booster.load_native_model(path).predict_raw(x)),
        np.asarray(b2.predict_raw(x)),
    )


def test_load_stage_corrupt_metadata_is_a_clear_error(tmp_path):
    from mmlspark_tpu.core.serialize import load_stage, save_stage
    from mmlspark_tpu.stages.basic import SelectColumns

    # missing metadata.json (hand-built / damaged directory)
    empty = tmp_path / "notastage"
    empty.mkdir()
    with pytest.raises(CorruptArtifactError) as e:
        load_stage(str(empty))
    assert "notastage" in str(e.value) and "metadata.json" in str(e.value)

    # truncated metadata.json
    path = str(tmp_path / "stage")
    save_stage(SelectColumns(cols=["a"]), path)
    StorageFaultInjector.truncate(os.path.join(path, "metadata.json"), 9)
    with pytest.raises(CorruptArtifactError) as e:
        load_stage(path)
    assert "truncated or garbled" in str(e.value)
    assert path in str(e.value)


# -- TPULearner kill-and-resume parity -----------------------------------------


def _learner_df():
    rng = np.random.default_rng(0)
    y = rng.integers(0, 2, 128)
    x = (rng.normal(size=(128, 6)) + y[:, None] * 2.5).astype(np.float32)
    return DataFrame.from_dict({"features": x, "label": y.astype(np.int64)})


def _learner():
    from mmlspark_tpu.dnn import mlp
    from mmlspark_tpu.models import TPULearner

    return TPULearner(
        mlp(6, [16], 2), epochs=6, batch_size=32, learning_rate=0.1, seed=7
    )


def test_learner_kill_at_every_checkpoint_boundary(tmp_path):
    """ISSUE 8 acceptance: a fit killed at ANY checkpoint boundary and
    resumed reaches the same loss trajectory as an uninterrupted fit —
    exact on the same backend (documented in docs/persistence.md)."""
    df = _learner_df()
    baseline = _learner().fit(df)._loss_history
    # epochs=6, checkpoint_every=2 -> commits after epochs 1, 3, 5
    for boundary in (1, 2, 3):
        d = str(tmp_path / f"kill{boundary}")
        inj = StorageFaultInjector()
        inj.crash_after_rename(nth=boundary)
        with pytest.raises(InjectedCrash):
            with installed(inj):
                _learner().fit(df, checkpoint_dir=d, checkpoint_every=2)
        resumed = _learner().fit(
            df, checkpoint_dir=d, checkpoint_every=2
        )._loss_history
        np.testing.assert_allclose(resumed, baseline, rtol=1e-6,
                                   err_msg=f"boundary {boundary}")


def test_learner_crash_before_commit_falls_back_and_recomputes(tmp_path):
    """A kill BEFORE a commit's rename loses that generation: resume falls
    back to the previous one and recomputes the lost epochs to the same
    trajectory."""
    df = _learner_df()
    baseline = _learner().fit(df)._loss_history
    d = str(tmp_path / "fallback")
    inj = StorageFaultInjector()
    inj.crash_before_rename(nth=2)
    with pytest.raises(InjectedCrash):
        with installed(inj):
            _learner().fit(df, checkpoint_dir=d, checkpoint_every=2)
    store = CheckpointStore(d)
    assert store.latest_generation() == 1  # gen 2 never committed
    resumed = _learner().fit(
        df, checkpoint_dir=d, checkpoint_every=2
    )._loss_history
    np.testing.assert_allclose(resumed, baseline, rtol=1e-6)


def test_learner_resume_after_complete_skips_training(tmp_path):
    df = _learner_df()
    d = str(tmp_path / "done")
    first = _learner().fit(df, checkpoint_dir=d, checkpoint_every=2)
    again = _learner().fit(df, checkpoint_dir=d, checkpoint_every=2)
    assert again._loss_history == first._loss_history
    scored = again.transform(df)
    assert scored["scores"].shape == (128, 2)


def test_learner_fingerprint_mismatch_refuses_resume(tmp_path):
    from mmlspark_tpu.dnn import mlp
    from mmlspark_tpu.models import TPULearner

    df = _learner_df()
    d = str(tmp_path / "fp")
    _learner().fit(df, checkpoint_dir=d, checkpoint_every=2)
    other = TPULearner(
        mlp(6, [16], 2), epochs=6, batch_size=32, learning_rate=0.05, seed=7
    )
    with pytest.raises(ValueError, match="fingerprint"):
        other.fit(df, checkpoint_dir=d)


def test_learner_refuses_epochs_below_checkpoint_cursor(tmp_path):
    """epochs stays outside the fingerprint so RAISING it extends a
    finished run — but a cursor PAST the requested horizon must refuse, or
    fit() would return an over-trained model with a wrong-length loss
    history for the shorter request."""
    from mmlspark_tpu.dnn import mlp
    from mmlspark_tpu.models import TPULearner

    df = _learner_df()
    d = str(tmp_path / "short")
    first = _learner().fit(df, checkpoint_dir=d, checkpoint_every=2)
    assert len(first._loss_history) == 6

    shorter = TPULearner(
        mlp(6, [16], 2), epochs=3, batch_size=32, learning_rate=0.1, seed=7
    )
    with pytest.raises(ValueError, match="epochs"):
        shorter.fit(df, checkpoint_dir=d, checkpoint_every=2)

    # the documented extension path still works: a higher horizon resumes
    # from the committed cursor and trains only the additional epochs
    longer = TPULearner(
        mlp(6, [16], 2), epochs=8, batch_size=32, learning_rate=0.1, seed=7
    )
    extended = longer.fit(df, checkpoint_dir=d, checkpoint_every=2)
    assert len(extended._loss_history) == 8
    assert extended._loss_history[:6] == first._loss_history


def test_learner_resumes_through_corrupted_latest_generation(tmp_path):
    """End to end across the whole subsystem: the newest checkpoint
    generation is bit-flipped on disk; resume quarantines it, falls back a
    generation, recomputes — and still matches the uninterrupted fit."""
    df = _learner_df()
    baseline = _learner().fit(df)._loss_history
    d = str(tmp_path / "bitrot")
    inj = StorageFaultInjector()
    inj.crash_after_rename(nth=2)
    with pytest.raises(InjectedCrash):
        with installed(inj):
            _learner().fit(df, checkpoint_dir=d, checkpoint_every=2)
    store = CheckpointStore(d)
    StorageFaultInjector.bit_flip(
        os.path.join(store._gen_dir(2), "train_state.npz")
    )
    resumed = _learner().fit(
        df, checkpoint_dir=d, checkpoint_every=2
    )._loss_history
    np.testing.assert_allclose(resumed, baseline, rtol=1e-6)
    assert glob.glob(os.path.join(d, "quarantine", "gen_*"))


# -- GBDT kill-and-resume parity -----------------------------------------------


def _gbdt_data(n=400, f=6, seed=1):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, f))
    y = (x[:, 0] + 0.5 * x[:, 1] ** 2
         + rng.normal(scale=0.2, size=n) > 0.5).astype(np.float64)
    return x, y


def _gbdt_fit(x, y, ckpt=None, **overrides):
    from mmlspark_tpu.gbdt.objectives import make_objective
    from mmlspark_tpu.gbdt.trainer import TrainConfig, train_booster

    cfg = dict(num_iterations=12, num_leaves=15, verbosity=0,
               bagging_fraction=0.8, bagging_freq=2, feature_fraction=0.7)
    cfg.update(overrides)
    return train_booster(
        x, y, make_objective("binary", num_class=2), TrainConfig(**cfg),
        checkpoint_dir=ckpt, checkpoint_every=4,
    )


def test_gbdt_kill_and_resume_bit_identical(tmp_path):
    """ISSUE 8 acceptance: a GBDT fit resumed mid-boosting matches the
    uninterrupted ensemble's predictions — bit-identical, with bagging AND
    feature-fraction sampling active (the rng sequences cross the kill)."""
    x, y = _gbdt_data()
    p0 = np.asarray(_gbdt_fit(x, y).predict_raw(x))
    # commits land after iterations 4, 8, 12 -> kill at boundaries 1 and 2
    for boundary in (1, 2):
        d = str(tmp_path / f"kill{boundary}")
        inj = StorageFaultInjector()
        inj.crash_after_rename(nth=boundary)
        with pytest.raises(InjectedCrash):
            with installed(inj):
                _gbdt_fit(x, y, ckpt=d)
        b = _gbdt_fit(x, y, ckpt=d)
        assert len(b.trees) == 12
        np.testing.assert_array_equal(np.asarray(b.predict_raw(x)), p0)


def test_gbdt_segmented_checkpointing_matches_unsegmented(tmp_path):
    x, y = _gbdt_data(n=300)
    p0 = np.asarray(_gbdt_fit(x, y).predict_raw(x))
    p1 = np.asarray(_gbdt_fit(x, y, ckpt=str(tmp_path / "seg")).predict_raw(x))
    np.testing.assert_array_equal(p0, p1)


def test_gbdt_multiclass_checkpoint_parity(tmp_path):
    from mmlspark_tpu.gbdt.objectives import make_objective
    from mmlspark_tpu.gbdt.trainer import TrainConfig, train_booster

    rng = np.random.default_rng(3)
    x = rng.normal(size=(240, 5))
    y = np.argmax(x[:, :3] + rng.normal(scale=0.3, size=(240, 3)), axis=1
                  ).astype(np.float64)
    cfg = TrainConfig(num_iterations=6, num_leaves=7, verbosity=0)
    obj = make_objective("multiclass", num_class=3)
    p0 = np.asarray(train_booster(x, y, obj, cfg).predict_raw(x))

    d = str(tmp_path / "mc")
    inj = StorageFaultInjector()
    inj.crash_after_rename(nth=1)
    with pytest.raises(InjectedCrash):
        with installed(inj):
            train_booster(x, y, make_objective("multiclass", num_class=3),
                          cfg, checkpoint_dir=d, checkpoint_every=3)
    b = train_booster(x, y, make_objective("multiclass", num_class=3),
                      cfg, checkpoint_dir=d, checkpoint_every=3)
    np.testing.assert_array_equal(np.asarray(b.predict_raw(x)), p0)


def test_gbdt_checkpoint_guards(tmp_path):
    x, y = _gbdt_data(n=128)
    with pytest.raises(ValueError, match="rf"):
        _gbdt_fit(x, y, ckpt=str(tmp_path / "rf"), boosting_type="rf")
    with pytest.raises(ValueError, match="early_stopping"):
        _gbdt_fit(x, y, ckpt=str(tmp_path / "es"), early_stopping_round=5)
    from mmlspark_tpu.gbdt.objectives import make_objective
    from mmlspark_tpu.gbdt.trainer import TrainConfig, train_booster

    with pytest.raises(ValueError, match="checkpoint_every"):
        train_booster(x, y, make_objective("binary", num_class=2),
                      TrainConfig(num_iterations=2, verbosity=0),
                      checkpoint_dir=str(tmp_path / "ce"), checkpoint_every=0)


def test_gbdt_fingerprint_mismatch_refuses_resume(tmp_path):
    x, y = _gbdt_data(n=200)
    d = str(tmp_path / "fp")
    _gbdt_fit(x, y, ckpt=d, num_iterations=4)
    with pytest.raises(ValueError, match="fingerprint"):
        _gbdt_fit(x, y, ckpt=d, num_iterations=4, learning_rate=0.27)


def test_gbdt_fingerprint_covers_warm_start_inputs(tmp_path):
    """init_raw folds into the checkpointed raw scores in segment one and
    init_model is replaced by the committed ensemble on resume — so
    resuming with either changed would silently drop the new value into a
    mixed ensemble. Both are part of the resume identity."""
    from mmlspark_tpu.gbdt.objectives import make_objective
    from mmlspark_tpu.gbdt.trainer import TrainConfig, train_booster

    x, y = _gbdt_data(n=200)
    cfg = TrainConfig(num_iterations=4, num_leaves=15, verbosity=0)

    d = str(tmp_path / "margins")
    margins = np.linspace(-0.5, 0.5, 200)
    train_booster(x, y, make_objective("binary", num_class=2), cfg,
                  init_raw=margins, checkpoint_dir=d, checkpoint_every=2)
    with pytest.raises(ValueError, match="fingerprint"):
        train_booster(x, y, make_objective("binary", num_class=2), cfg,
                      checkpoint_dir=d, checkpoint_every=2)

    warm = _gbdt_fit(x, y, num_iterations=4)
    d2 = str(tmp_path / "warm")
    train_booster(x, y, make_objective("binary", num_class=2), cfg,
                  init_model=warm, checkpoint_dir=d2, checkpoint_every=2)
    with pytest.raises(ValueError, match="fingerprint"):
        train_booster(x, y, make_objective("binary", num_class=2), cfg,
                      checkpoint_dir=d2, checkpoint_every=2)


def test_gbdt_estimator_checkpoint_kill_and_resume(tmp_path):
    """The estimator surface: LightGBMRegressor(checkpoint_dir=...) killed
    mid-fit resumes through the same Params and matches the uninterrupted
    model's predictions."""
    from mmlspark_tpu.gbdt.estimators import LightGBMRegressor

    rng = np.random.default_rng(5)
    x = rng.normal(size=(250, 5)).astype(np.float64)
    yv = x[:, 0] * 2.0 + np.sin(x[:, 1]) + rng.normal(scale=0.1, size=250)
    df = DataFrame.from_dict({"features": x, "label": yv})

    def est(ckpt=None):
        kw = dict(num_iterations=6, num_leaves=7, verbosity=0,
                  checkpoint_every=3)
        if ckpt:
            kw["checkpoint_dir"] = ckpt
        return LightGBMRegressor(**kw)

    p0 = est().fit(df).transform(df)["prediction"]
    d = str(tmp_path / "est")
    inj = StorageFaultInjector()
    inj.crash_after_rename(nth=1)
    with pytest.raises(InjectedCrash):
        with installed(inj):
            est(d).fit(df)
    assert CheckpointStore(d).latest_generation() == 1
    model = est(d).fit(df)
    np.testing.assert_array_equal(
        np.asarray(model.transform(df)["prediction"]), np.asarray(p0)
    )


def test_injector_rearm_after_consumed_fault_fires(tmp_path):
    """Occurrence counts are per-fault, not shared per (op, match): after
    one armed fault fires and is consumed, re-arming the same operation on
    the SAME injector counts from zero — a reused injector must never run
    a 'fault' scenario with no fault actually injected."""
    inj = StorageFaultInjector()
    st = CheckpointStore(str(tmp_path), fault_injector=inj)
    inj.crash_after_rename(nth=1)
    with pytest.raises(InjectedCrash):
        st.save(_payload(b"one"))
    inj.crash_before_rename(nth=1)  # re-arm: must fire on the NEXT rename
    with pytest.raises(InjectedCrash):
        st.save(_payload(b"two"))
    assert st.generations() == [1]  # gen 2 never committed


def test_custom_save_to_dir_receives_existing_dir(tmp_path):
    """The serialize custom protocol's pre-ISSUE-8 guarantee holds: the
    target directory exists when a duck-typed save_to_dir runs, so external
    classes that open files without makedirs keep round-tripping."""
    from mmlspark_tpu.core.serialize import _load_complex, _save_complex

    class NoMakedirs:
        def __init__(self, v=0):
            self.v = v

        def save_to_dir(self, path):
            with open(os.path.join(path, "v.json"), "w") as f:
                json.dump({"v": self.v}, f)

        @classmethod
        def load_from_dir(cls, path):
            with open(os.path.join(path, "v.json")) as f:
                return cls(json.load(f)["v"])

    kind = _save_complex(NoMakedirs(7), str(tmp_path), "val")
    assert kind == "custom"
    # loading resolves the class by import path; this local class can't
    # round-trip cross-process, but the marker must exist and name it
    with open(tmp_path / "val" / "_custom.json") as f:
        assert "NoMakedirs" in json.load(f)["class"]
    with open(tmp_path / "val" / "v.json") as f:
        assert json.load(f)["v"] == 7


def test_nested_pipeline_stage_save_roundtrip(tmp_path):
    """Nested stage lists write straight into the outer staging tree (one
    fsync pass, one atomic swap) and still round-trip through load_stage."""
    from mmlspark_tpu.core.pipeline import Pipeline
    from mmlspark_tpu.core.serialize import load_stage, save_stage
    from mmlspark_tpu.stages.basic import DropColumns, SelectColumns

    pipe = Pipeline(stages=[SelectColumns(cols=["a", "b"]),
                            DropColumns(cols=["b"])])
    path = str(tmp_path / "pipe")
    save_stage(pipe, path)
    loaded = load_stage(path)
    stages = loaded.get("stages")
    assert [type(s).__name__ for s in stages] == ["SelectColumns",
                                                  "DropColumns"]
    assert stages[0].get("cols") == ["a", "b"]
    assert stages[1].get("cols") == ["b"]


def test_network_spec_only_save_preserves_weights(tmp_path):
    """Network.save_to_dir(path) with variables omitted keeps its merge
    semantics through the atomic swap: existing weights survive."""
    import jax

    from mmlspark_tpu.dnn import mlp
    from mmlspark_tpu.dnn.network import Network, NetworkBundle

    net = mlp(4, [8], 2)
    v = jax.device_get(net.init(jax.random.PRNGKey(0)))
    path = str(tmp_path / "model")
    NetworkBundle(net, v).save_to_dir(path)
    net.save_to_dir(path)  # spec-only overwrite
    loaded = NetworkBundle.load_from_dir(path)  # weights still there
    np.testing.assert_array_equal(
        loaded.variables["params"]["dense_0"]["kernel"],
        np.asarray(v["params"]["dense_0"]["kernel"]),
    )


def test_publish_dir_trash_gc_with_glob_metachars(tmp_path):
    """Stale-trash reclamation escapes the destination path: brackets and
    stars in a run directory name must neither break the GC nor let it
    delete a sibling's park."""
    from mmlspark_tpu.io.checkpoint import staged_dir

    base = tmp_path / "runs" / "v[1]"
    base.mkdir(parents=True)
    dst = str(base / "artifact")
    for round_i in range(2):
        with staged_dir(dst) as tmp:
            with open(os.path.join(tmp, "data.txt"), "w") as f:
                f.write(f"round {round_i}")
    # a stale park left by a simulated kill is reclaimed on the next save
    stale = str(base / "artifact.trash-stale")
    os.makedirs(stale)
    with staged_dir(dst) as tmp:
        with open(os.path.join(tmp, "data.txt"), "w") as f:
            f.write("round 2")
    assert not os.path.exists(stale)
    with open(os.path.join(dst, "data.txt")) as f:
        assert f.read() == "round 2"


def test_checkpointed_booster_drops_resume_capture(tmp_path):
    x, y = _gbdt_data(n=200)
    b = _gbdt_fit(x, y, ckpt=str(tmp_path / "cap"), num_iterations=4)
    assert not hasattr(b, "_resume_capture")


def test_checkpoint_roundtrip_helpers():
    arrays = {"a": np.arange(7, dtype=np.int32),
              "b": np.ones((2, 3), np.float32)}
    out = unpack_arrays(pack_arrays(arrays))
    assert set(out) == {"a", "b"}
    np.testing.assert_array_equal(out["a"], arrays["a"])
    np.testing.assert_array_equal(out["b"], arrays["b"])


def test_pack_arrays_rejects_object_dtype():
    """np.savez would pickle an object array, committing a generation that
    every allow_pickle=False load then fails to unpack — an
    integrity-verified checkpoint that can never be resumed. Refused at
    pack time instead."""
    with pytest.raises(TypeError, match="object"):
        pack_arrays({"bad": np.array([{"a": 1}, None], dtype=object)})
