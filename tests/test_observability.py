"""Unified observability layer tests (ISSUE 5): the metrics registry
(labelled instruments, streaming quantile sketch, Prometheus exposition,
thread-safety under hammer), the request tracer (span trees, propagation,
JSONL + Chrome export, bounded retention), the registry-backed profiling
counters, and the ServingServer surfaces (/metrics, /healthz, per-request
span path, slow-request logging)."""

import http.client
import json
import logging
import threading
import time

import numpy as np
import pytest

from mmlspark_tpu import obs
from mmlspark_tpu.obs.metrics import (
    MetricsRegistry,
    QuantileSketch,
    parse_prometheus,
)
from mmlspark_tpu.obs.tracing import Tracer, current_span
from mmlspark_tpu.utils.profiling import (
    ServingPipelineCounters,
    StageTimer,
    dataplane_counters,
)

N_THREADS = 8
N_OPS = 2000


def _hammer(fn, n_threads=N_THREADS):
    threads = [
        threading.Thread(target=fn, args=(i,)) for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


# -- quantile sketch ----------------------------------------------------------


class TestQuantileSketch:
    def test_small_stream_is_exact(self):
        s = QuantileSketch(k=128)
        for v in range(1, 101):
            s.add(float(v))
        assert s.count == 100 and s.min == 1.0 and s.max == 100.0
        assert s.quantile(0.0) == 1.0
        assert s.quantile(1.0) == 100.0
        assert abs(s.quantile(0.5) - 50.0) <= 1.0

    def test_bounded_memory_and_monotone_quantiles(self):
        s = QuantileSketch(k=64)
        rng = np.random.default_rng(0)
        values = rng.exponential(10.0, size=100_000)
        for v in values:
            s.add(float(v))
        # bounded: levels hold at most k items each, level count is
        # logarithmic — far below the stream length
        retained = sum(len(lvl) for lvl in s._levels)
        assert retained <= 64 * len(s._levels) < 2000
        qs = [s.quantile(q) for q in (0.1, 0.25, 0.5, 0.75, 0.95, 0.99)]
        assert qs == sorted(qs), qs
        assert all(s.min <= q <= s.max for q in qs)
        # rank accuracy sanity: p50 of an exp(10) stream is ~6.93
        assert abs(qs[2] - np.median(values)) / np.median(values) < 0.25

    def test_empty_is_nan(self):
        s = QuantileSketch()
        assert s.quantile(0.5) != s.quantile(0.5)  # NaN


# -- instruments under concurrency (satellite: exact totals) ------------------


class TestInstrumentsConcurrent:
    def test_counter_exact_total_across_threads(self):
        reg = MetricsRegistry()
        c = reg.counter("hammer_total", "t", ("worker",))

        def work(i):
            child = c.labels(worker=str(i % 2))
            for _ in range(N_OPS):
                child.inc()

        _hammer(work)
        total = sum(
            child.value() for _key, child in c.children()
        )
        assert total == N_THREADS * N_OPS
        assert c.labels(worker="0").value() == N_THREADS * N_OPS / 2

    def test_histogram_exact_count_sum_and_sketch_bounds(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_ms", "t", quantiles=(0.5, 0.95, 0.99))

        def work(i):
            for j in range(N_OPS):
                h.observe(float(j % 100))

        _hammer(work)
        assert h.count() == N_THREADS * N_OPS
        # integers sum exactly in f64 at this magnitude
        assert h.sum() == N_THREADS * sum(j % 100 for j in range(N_OPS))
        q50, q95, q99 = (h.quantile(q) for q in (0.5, 0.95, 0.99))
        assert 0.0 <= q50 <= q95 <= q99 <= 99.0

    def test_gauge_set_max_races_to_true_peak(self):
        reg = MetricsRegistry()
        g = reg.gauge("peak", "t")

        def work(i):
            for j in range(N_OPS):
                g.labels().set_max(float(i * N_OPS + j))

        _hammer(work)
        assert g.value() == float(N_THREADS * N_OPS - 1)

    def test_counter_rejects_negative(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError, match="only go up"):
            reg.counter("c_total").inc(-1)


# -- registry semantics -------------------------------------------------------


class TestRegistry:
    def test_get_or_create_returns_same_family(self):
        reg = MetricsRegistry()
        assert reg.counter("a_total") is reg.counter("a_total")

    def test_type_mismatch_raises(self):
        reg = MetricsRegistry()
        reg.counter("x_total")
        with pytest.raises(ValueError, match="re-registered"):
            reg.gauge("x_total")

    def test_label_mismatch_raises(self):
        reg = MetricsRegistry()
        reg.counter("y_total", labelnames=("a",))
        with pytest.raises(ValueError, match="re-registered"):
            reg.counter("y_total", labelnames=("b",))

    def test_disabled_registry_noops(self):
        reg = MetricsRegistry()
        c = reg.counter("z_total")
        h = reg.histogram("z_ms")
        reg.set_enabled(False)
        c.inc()
        h.observe(5.0)
        assert c.value() == 0 and h.count() == 0
        reg.set_enabled(True)
        c.inc()
        assert c.value() == 1

    def test_render_parse_round_trip_with_escaping(self):
        reg = MetricsRegistry()
        c = reg.counter("esc_total", "weird labels", ("path",))
        c.labels(path='a"b\\c\nd').inc(3)
        g = reg.gauge("plain", "no labels")
        g.set(2.5)
        text = reg.render_prometheus()
        parsed = parse_prometheus(text)
        assert parsed[("plain", ())] == 2.5
        assert parsed[("esc_total", (("path", 'a"b\\c\nd'),))] == 3.0

    def test_literal_backslash_n_round_trips(self):
        """'C:\\nightly' must not decode to a newline: unescaping is a
        left-to-right scan, not ordered str.replace."""
        reg = MetricsRegistry()
        reg.counter("bs_total", "", ("path",)).labels(
            path="C:\\nightly"
        ).inc()
        parsed = parse_prometheus(reg.render_prometheus())
        assert parsed[("bs_total", (("path", "C:\\nightly"),))] == 1.0

    def test_histogram_quantile_mismatch_raises(self):
        reg = MetricsRegistry()
        reg.histogram("q_ms", quantiles=(0.5,))
        with pytest.raises(ValueError, match="re-registered"):
            reg.histogram("q_ms", quantiles=(0.5, 0.999))

    def test_callback_gauge_reads_at_scrape(self):
        reg = MetricsRegistry()
        box = {"v": 1.0}
        reg.gauge("cb").set_function(lambda: box["v"])
        assert parse_prometheus(reg.render_prometheus())[("cb", ())] == 1.0
        box["v"] = 7.0
        assert parse_prometheus(reg.render_prometheus())[("cb", ())] == 7.0


# -- registry-backed profiling counters ---------------------------------------


class TestProfilingCountersConcurrent:
    def test_dataplane_counters_exact_under_hammer(self):
        c = dataplane_counters()
        before = c.snapshot()

        def work(i):
            for _ in range(N_OPS):
                c.record_h2d(8)
                c.record_d2h(4)

        _hammer(work)
        delta = c.delta(before)
        assert delta["h2d_transfers"] == N_THREADS * N_OPS
        assert delta["h2d_bytes"] == N_THREADS * N_OPS * 8
        assert delta["d2h_transfers"] == N_THREADS * N_OPS
        assert delta["d2h_bytes"] == N_THREADS * N_OPS * 4

    def test_fresh_dataplane_view_starts_at_zero(self):
        from mmlspark_tpu.utils.profiling import DataplaneCounters

        dataplane_counters().record_h2d(64)  # pre-existing process traffic
        fresh = DataplaneCounters()
        assert fresh.snapshot() == {
            k: 0 for k in DataplaneCounters._FIELDS
        }

    def test_dataplane_reset_is_view_local(self):
        c = dataplane_counters()
        c.record_h2d(1)
        c.reset()
        assert c.snapshot()["h2d_transfers"] == 0
        c.record_h2d(1)
        assert c.h2d_transfers == 1  # attribute surface preserved

    def test_serving_pipeline_counters_exact_under_hammer(self):
        p = ServingPipelineCounters()
        reps = 200

        def work(i):
            for _ in range(reps):
                with p.stage("parse", rows=2):
                    pass
                with p.stage("reply"):
                    pass
                p.enter_in_flight()
                p.record_dispatch(immediate=(i % 2 == 0))
                p.exit_in_flight()

        _hammer(work)
        s = p.summary()
        assert s["parse_batches"] == N_THREADS * reps
        assert s["reply_batches"] == N_THREADS * reps
        assert s["rows"] == N_THREADS * reps * 2
        assert (
            s["immediate_dispatches"] + s["coalesced_dispatches"]
            == N_THREADS * reps
        )
        assert p.in_flight == 0
        assert 1 <= p.in_flight_peak <= N_THREADS
        assert s["parse_occupancy"] >= 0.0

    def test_serving_counters_are_scrapeable(self):
        p = ServingPipelineCounters(engine_label="scrape-test")
        with p.stage("score"):
            pass
        text = obs.registry().render_prometheus()
        parsed = parse_prometheus(text)
        key = (
            "serving_stage_batches_total",
            (("engine", "scrape-test"), ("stage", "score")),
        )
        assert parsed[key] == 1.0


# -- StageTimer thread-safety (satellite) -------------------------------------


def test_stage_timer_concurrent_accumulation():
    t = StageTimer()

    def work(i):
        for _ in range(500):
            with t.time("shared"):
                pass
            with t.time(f"own-{i}"):
                pass

    _hammer(work)
    rep = t.report()
    # no lost names, and the shared accumulator saw every block
    assert set(rep) == {"shared"} | {f"own-{i}" for i in range(N_THREADS)}
    assert rep["shared"] > 0


# -- profile_to / annotate log in finally (satellite) -------------------------


def test_profile_to_logs_wall_clock_when_block_raises(tmp_path, caplog):
    from mmlspark_tpu.utils import profile_to

    with caplog.at_level(logging.INFO, logger="mmlspark_tpu.profiling"):
        with pytest.raises(RuntimeError, match="boom"):
            with profile_to(str(tmp_path / "trace")):
                raise RuntimeError("boom")
    assert any("profile_to" in r.message for r in caplog.records)


def test_annotate_logs_wall_clock_when_block_raises(caplog):
    from mmlspark_tpu.utils import annotate

    with caplog.at_level(logging.DEBUG, logger="mmlspark_tpu.profiling"):
        with pytest.raises(ValueError, match="nope"):
            with annotate("failing-region"):
                raise ValueError("nope")
    assert any("failing-region" in r.message for r in caplog.records)


# -- tracer -------------------------------------------------------------------


class TestTracer:
    def test_context_nesting_builds_parent_links(self):
        tr = Tracer()
        with tr.span("root") as root:
            assert current_span() is root
            with tr.span("child") as child:
                assert child.parent_id == root.span_id
                assert child.trace_id == root.trace_id
        assert current_span() is None
        names = [s.name for s in tr.spans(root.trace_id)]
        assert names == ["child", "root"]  # children end first

    def test_explicit_parent_crosses_threads(self):
        tr = Tracer()
        root = tr.start_span("http")
        done = threading.Event()
        holder = {}

        def worker():
            with tr.activate(root):
                with tr.span("score") as s:
                    holder["span"] = s
            done.set()

        threading.Thread(target=worker).start()
        assert done.wait(5)
        tr.end_span(root)
        assert holder["span"].parent_id == root.span_id
        assert holder["span"].trace_id == root.trace_id

    def test_add_span_retroactive(self):
        tr = Tracer()
        root = tr.start_span("http")
        t0 = time.monotonic()
        span = tr.add_span("parse", root, t0, t0 + 0.25, attrs={"n": 4})
        tr.end_span(root)
        assert span.parent_id == root.span_id
        assert abs(span.duration_ms() - 250.0) < 1.0

    def test_error_attr_on_raise(self):
        tr = Tracer()
        with pytest.raises(KeyError):
            with tr.span("boom") as s:
                raise KeyError("x")
        assert "KeyError" in s.attrs["error"]

    def test_bounded_retention(self):
        tr = Tracer(max_spans=10)
        for i in range(50):
            with tr.span(f"s{i}"):
                pass
        spans = tr.spans()
        assert len(spans) == 10
        assert spans[-1].name == "s49"

    def test_disabled_tracer_noops(self):
        tr = Tracer()
        tr.set_enabled(False)
        with tr.span("invisible") as s:
            assert not s.recording
            s.set_attribute("k", "v")  # no-op, no crash
        assert tr.spans() == []
        tr.set_enabled(True)

    def test_jsonl_export(self, tmp_path):
        tr = Tracer()
        with tr.span("a", key="v"):
            with tr.span("b"):
                pass
        path = str(tmp_path / "spans.jsonl")
        n = tr.export_jsonl(path)
        assert n == 2
        lines = [json.loads(x) for x in open(path).read().splitlines()]
        by_name = {d["name"]: d for d in lines}
        assert by_name["b"]["parent_id"] == by_name["a"]["span_id"]
        assert by_name["a"]["attrs"] == {"key": "v"}
        assert by_name["a"]["duration_ms"] >= 0

    def test_chrome_trace_export(self, tmp_path):
        tr = Tracer()
        with tr.span("stage") as s:
            s.add_event("h2d_upload", nbytes=64)
        path = str(tmp_path / "trace.json")
        n = tr.export_chrome_trace(path)
        assert n == 2  # one X span + one i event
        doc = json.load(open(path))
        evs = doc["traceEvents"]
        complete = [e for e in evs if e["ph"] == "X"]
        instants = [e for e in evs if e["ph"] == "i"]
        assert complete[0]["name"] == "stage"
        assert {"ts", "dur", "pid", "tid"} <= set(complete[0])
        assert instants[0]["name"] == "h2d_upload"
        assert instants[0]["args"] == {"nbytes": 64}


def test_obs_disabled_scopes_both_layers():
    with obs.disabled():
        assert not obs.registry().enabled
        assert not obs.tracer().enabled
    assert obs.registry().enabled and obs.tracer().enabled


# -- pipeline spans + stage histograms ----------------------------------------


def test_pipeline_transform_emits_stage_spans_and_histograms():
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.core.pipeline import PipelineModel
    from mmlspark_tpu.stages.basic import DropColumns, RenameColumn

    tr = obs.tracer()
    tr.clear()
    df = DataFrame.from_dict({"a": np.arange(4.0), "b": np.arange(4.0)})
    pm = PipelineModel([
        RenameColumn(input_col="a", output_col="a2"),
        DropColumns(cols=["b"]),
    ])
    with tr.span("request") as root:
        pm.transform(df)
    names = [s.name for s in tr.spans(root.trace_id)]
    assert "stage:RenameColumn" in names and "stage:DropColumns" in names
    hist = obs.registry().histogram(
        "pipeline_stage_seconds",
        "Wall seconds per pipeline stage transform", ("stage",),
    )
    assert hist.labels(stage="DropColumns").count() >= 1


def test_gbdt_fit_emits_phase_metrics():
    from mmlspark_tpu.gbdt import LightGBMClassifier
    from mmlspark_tpu.utils import generate_dataset

    hist = obs.registry().histogram(
        "gbdt_phase_seconds", "Wall seconds per GBDT training phase",
        ("phase",),
    )
    before = hist.labels(phase="binning").count()
    df = generate_dataset({"features": "vector", "label": "label"}, 60, seed=1)
    LightGBMClassifier(num_iterations=2, num_leaves=4).fit(df)
    assert hist.labels(phase="binning").count() == before + 1


# -- serving integration ------------------------------------------------------


def _staged_handler():
    import jax.numpy as jnp

    from mmlspark_tpu.core.dataframe import DataType
    from mmlspark_tpu.serving import (
        StagedServingHandler,
        make_reply,
        parse_request,
    )

    class Staged(StagedServingHandler):
        def parse(self, df):
            parsed = parse_request(df, {"x": DataType.VECTOR})
            parsed.column("x").device_values()
            return parsed

        def score(self, df):
            y = df.column("x").device_values() * 2.0
            return df.with_column("y", y, DataType.VECTOR)

        def reply(self, df):
            return make_reply(df, "y")

    return Staged()


def _post(port, route, payload):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    conn.request("POST", route, json.dumps(payload).encode(),
                 {"Content-Type": "application/json"})
    r = conn.getresponse()
    body = r.read()
    conn.close()
    return r.status, body


def _get(port, route):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    conn.request("GET", route)
    r = conn.getresponse()
    body = r.read()
    conn.close()
    return r.status, body


class TestServingObservability:
    def test_metrics_healthz_and_span_tree(self, tmp_path):
        from mmlspark_tpu.serving import ServingServer

        tr = obs.tracer()
        tr.clear()
        with ServingServer(
            _staged_handler(), api_name="score", mode="micro_batch"
        ) as srv:
            for i in range(3):
                status, body = _post(srv.port, "/score", {"x": [1.0, float(i)]})
                assert status == 200, body

            # /metrics: Prometheus text with the acceptance families
            status, body = _get(srv.port, "/metrics")
            assert status == 200
            parsed = parse_prometheus(body.decode())
            names = {name for name, _ in parsed}
            for required in (
                "serving_request_latency_ms_count",
                "serving_stage_busy_seconds_total",
                "serving_stage_occupancy",
                "serving_queue_depth",
                "dataplane_h2d_transfers_total",
                "dataplane_d2h_transfers_total",
                "dataplane_compiles_total",
            ):
                assert required in names, f"missing {required}"
            # the latency summary carries p50/p99 quantile series
            assert any(
                name == "serving_request_latency_ms"
                and dict(labels).get("quantile") == "0.99"
                for name, labels in parsed
            )

            # /healthz: live engine state
            status, body = _get(srv.port, "/healthz")
            health = json.loads(body)
            assert status == 200, health
            assert health["status"] == "ok"
            assert health["threads"] == {"dispatch": True, "score": True}
            assert health["queue_depth"] == 0
            assert health["last_dispatch_age_s"] is not None
            assert health["uptime_s"] > 0

            # unknown routes still 404
            status, _ = _post(srv.port, "/nope", {})
            assert status == 404

        # span tree: every request's trace has the full stage path
        http_spans = [s for s in tr.spans() if s.name == "http"]
        assert len(http_spans) >= 3
        tree = {s.name for s in tr.spans(http_spans[-1].trace_id)}
        assert {"http", "parse", "score", "reply"} <= tree
        root = http_spans[-1]
        children = [
            s for s in tr.spans(root.trace_id)
            if s.parent_id == root.span_id
        ]
        assert {"parse", "score", "reply"} <= {s.name for s in children}
        assert root.attrs["status_code"] == 200
        assert root.attrs["request_id"]

        # exports: JSONL and Chrome trace (Perfetto-loadable)
        jl = str(tmp_path / "req.jsonl")
        assert tr.export_jsonl(jl, trace_id=root.trace_id) >= 4
        ct = str(tmp_path / "req.trace.json")
        assert tr.export_chrome_trace(ct, trace_id=root.trace_id) >= 4
        doc = json.load(open(ct))
        assert {"http", "parse", "score", "reply"} <= {
            e["name"] for e in doc["traceEvents"] if e["ph"] == "X"
        }

    def test_health_degrades_on_stop(self):
        from mmlspark_tpu.serving import ServingServer

        srv = ServingServer(
            _staged_handler(), api_name="score", mode="micro_batch"
        ).start()
        ok, info = srv.health()
        assert ok and info["status"] == "ok"
        srv.stop()
        ok, info = srv.health()
        assert not ok and info["status"] == "stopping"

    def test_stop_unregisters_callback_series(self):
        """Scrape-time gauges close over the server object; stop() must
        remove them so the registry neither pins stopped servers nor keeps
        reporting their stale liveness."""
        from mmlspark_tpu.serving import ServingServer

        srv = ServingServer(
            _staged_handler(), api_name="score", mode="micro_batch"
        ).start()
        label = srv._obs_label
        live = parse_prometheus(obs.registry().render_prometheus())
        assert ("serving_queue_depth", (("engine", label),)) in live
        assert (
            "serving_stage_occupancy",
            (("engine", label), ("stage", "parse")),
        ) in live
        srv.stop()
        after = parse_prometheus(obs.registry().render_prometheus())
        assert ("serving_queue_depth", (("engine", label),)) not in after
        assert not any(
            name == "serving_stage_occupancy"
            and dict(labels).get("engine") == label
            for name, labels in after
        )
        # cumulative counter series survive (Prometheus append-only)
        assert any(
            name == "serving_stage_batches_total"
            and dict(labels).get("engine") == label
            for name, labels in after
        )

    def test_continuous_mode_has_endpoints_and_spans(self):
        from mmlspark_tpu.serving import ServingServer

        tr = obs.tracer()
        tr.clear()

        def handler(df):
            from mmlspark_tpu.serving import make_reply, parse_request

            parsed = parse_request(df)
            vals = np.asarray([float(v) for v in parsed["x"]])
            from mmlspark_tpu.core.dataframe import DataType

            return make_reply(
                parsed.with_column("y", vals * 2.0, DataType.DOUBLE), "y"
            )

        with ServingServer(handler, api_name="cont") as srv:
            status, _ = _post(srv.port, "/cont", {"x": 2.0})
            assert status == 200
            status, body = _get(srv.port, "/healthz")
            assert status == 200
            assert json.loads(body)["threads"] == {}  # no engine threads
        http_spans = [s for s in tr.spans() if s.name == "http"]
        assert http_spans
        tree = {s.name for s in tr.spans(http_spans[-1].trace_id)}
        assert {"http", "score"} <= tree  # continuous: handler IS the score

    def test_slow_request_logging_carries_span_path(self, caplog):
        from mmlspark_tpu.serving import ServingServer

        with caplog.at_level(logging.WARNING, logger="mmlspark_tpu.serving"):
            with ServingServer(
                _staged_handler(), api_name="score", mode="micro_batch",
                slow_request_ms=0.0,  # everything is an outlier
            ) as srv:
                status, _ = _post(srv.port, "/score", {"x": [1.0, 2.0]})
                assert status == 200
        slow = [
            json.loads(r.getMessage()) for r in caplog.records
            if "slow_request" in r.message
        ]
        assert slow, "no slow-request log emitted"
        rec = slow[0]
        assert rec["event"] == "slow_request"
        # structured fields: the full span path plus the trace id that
        # links the log line to its trace in the flight recorder
        assert "http" in rec["span_path"]
        assert rec["latency_ms"] >= 0.0
        assert rec["trace_id"]

    def test_distributed_gateway_serves_obs_endpoints(self):
        from mmlspark_tpu.serving import DistributedServingServer

        with DistributedServingServer(
            _staged_handler, n_workers=2, api_name="pool",
            mode="micro_batch",
        ) as srv:
            assert _post(srv.port, "/pool", {"x": [1.0, 1.0]})[0] == 200
            status, body = _get(srv.port, "/metrics")
            assert status == 200
            assert "serving_request_latency_ms" in body.decode()
            status, body = _get(srv.port, "/healthz")
            health = json.loads(body)
            assert status == 200, health
            assert health["status"] == "ok"
            assert len(health["workers"]) == 2
            assert all(w["status"] == "ok" for w in health["workers"])

    def test_request_latency_histogram_labels_status(self):
        from mmlspark_tpu.serving import ServingServer

        with ServingServer(
            _staged_handler(), api_name="score", mode="micro_batch"
        ) as srv:
            label = srv._obs_label
            assert _post(srv.port, "/score", {"x": [1.0, 1.0]})[0] == 200
            hist = obs.registry().histogram(
                "serving_request_latency_ms",
                "End-to-end request latency at the HTTP edge",
                ("engine", "code"),
            )
            assert hist.labels(engine=label, code="200").count() >= 1
