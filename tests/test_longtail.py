"""Tests: FluentAPI sugar, udfs, PowerBI sink, cognitive-style clients."""

import json
import threading

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame, DataType
from mmlspark_tpu.io import powerbi
from mmlspark_tpu.io.cognitive import AnomalyDetector, TextSentiment
from mmlspark_tpu.stages.basic import UDFTransformer
from mmlspark_tpu.stages.udfs import get_value_at, get_value_at_column


class TestFluentAPI:
    def test_ml_transform_chains(self):
        from mmlspark_tpu.stages.basic import DropColumns, RenameColumn

        df = DataFrame.from_dict({"a": [1.0, 2.0], "b": [3.0, 4.0]})
        out = df.ml_transform(
            RenameColumn(input_col="a", output_col="a2"),
            DropColumns(cols=["b"]),
        )
        assert out.columns == ["a2"]

    def test_ml_fit(self):
        from mmlspark_tpu.stages.basic import ClassBalancer

        df = DataFrame.from_dict({"label": np.array([0.0, 0.0, 1.0])})
        model = df.ml_fit(ClassBalancer(input_col="label"))
        assert model.transform(df)["weight"][2] == 2.0


class TestUdfs:
    def test_get_value_at(self):
        df = DataFrame.from_dict({"v": np.arange(12.0).reshape(4, 3)})
        stage = UDFTransformer(input_col="v", output_col="second",
                               udf=get_value_at(1))
        out = stage.transform(df)
        np.testing.assert_allclose(out["second"], [1.0, 4.0, 7.0, 10.0])

    def test_get_value_at_column(self):
        vals = np.arange(6.0).reshape(3, 2)
        np.testing.assert_allclose(get_value_at_column(vals, 0), [0, 2, 4])


def _start_capture_server(status=200, body=b"{}"):
    """Tiny HTTP server that records JSON request bodies."""
    import http.server

    captured = []

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            captured.append(
                (self.path, dict(self.headers), self.rfile.read(n))
            )
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, captured


class TestPowerBI:
    def test_write_batches(self):
        httpd, captured = _start_capture_server()
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}/push"
            df = DataFrame.from_dict(
                {"name": np.array(list("abcde"), object), "x": np.arange(5.0)},
                types={"name": DataType.STRING},
            )
            sent = powerbi.write(df, url, {"batchSize": 2})
            assert sent == 3  # 2+2+1
            rows = [r for _, _, b in captured for r in json.loads(b)]
            assert len(rows) == 5
            assert {"name": "a", "x": 0.0} in rows
        finally:
            httpd.shutdown()

    def test_http_error_raises(self):
        httpd, _ = _start_capture_server(status=503)
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}/push"
            df = DataFrame.from_dict({"x": np.arange(3.0)})
            with pytest.raises(RuntimeError, match="HTTP 503"):
                powerbi.write(df, url, {"batchSize": 3})
        finally:
            httpd.shutdown()

    def test_rejects_unknown_option(self):
        df = DataFrame.from_dict({"x": np.arange(2.0)})
        with pytest.raises(ValueError, match="not applicable"):
            powerbi.write(df, "http://x", {"bogus": "1"})


class TestCognitive:
    def test_text_sentiment_contract(self):
        httpd, captured = _start_capture_server(
            body=json.dumps(
                {"documents": [{"id": "1", "score": 0.9}], "errors": []}
            ).encode()
        )
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}/sentiment"
            df = DataFrame.from_dict(
                {"text": np.array(["great product", "terrible"], object)},
                types={"text": DataType.STRING},
            )
            ts = TextSentiment(
                url=url, subscription_key="secret-key",
                input_col="text", output_col="sentiment",
            )
            out = ts.transform(df)
            assert "sentiment" in out.columns
            got = out["sentiment"][0]
            assert got["documents"][0]["score"] == 0.9
            # request contract: documents JSON + key header
            path, headers, body = captured[0]
            sent = json.loads(body)
            assert sent["documents"][0]["text"] == "great product"
            assert sent["documents"][0]["language"] == "en"
            assert headers.get("Ocp-Apim-Subscription-Key") == "secret-key"
        finally:
            httpd.shutdown()

    def test_anomaly_detector_body(self):
        httpd, captured = _start_capture_server(
            body=json.dumps({"isAnomaly": [False, True]}).encode()
        )
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}/anomaly"
            series = np.empty(1, object)
            series[0] = [
                {"timestamp": "2026-01-01T00:00:00Z", "value": 1.0},
                {"timestamp": "2026-01-02T00:00:00Z", "value": 99.0},
            ]
            df = DataFrame.from_dict({"series": series})
            ad = AnomalyDetector(url=url, input_col="series", output_col="verdict")
            out = ad.transform(df)
            assert out["verdict"][0]["isAnomaly"] == [False, True]
            sent = json.loads(captured[0][2])
            assert sent["granularity"] == "daily"
            assert len(sent["series"]) == 2
        finally:
            httpd.shutdown()


class TestCognitiveFamilies:
    """Round-5 sweep (VERDICT item 3): TextAnalytics / ComputerVision / Face
    families over CognitiveServiceBase, each validated against a local mock."""

    def _run(self, stage_cls, value, value_type, response, **kwargs):
        httpd, captured = _start_capture_server(
            body=json.dumps(response).encode()
        )
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}/svc"
            df = DataFrame.from_dict(
                {"x": np.array([value], object)}, types={"x": value_type}
            )
            stage = stage_cls(url=url, subscription_key="k", input_col="x",
                              output_col="out", **kwargs)
            out = stage.transform(df)
            path, headers, body = captured[0]
            assert headers.get("Ocp-Apim-Subscription-Key") == "k"
            return out["out"][0], path, json.loads(body)
        finally:
            httpd.shutdown()

    def test_language_detector(self):
        from mmlspark_tpu.io.cognitive import LanguageDetector

        resp = {"documents": [{"id": "1", "detectedLanguages":
                               [{"name": "English", "score": 1.0}]}]}
        got, _, sent = self._run(
            LanguageDetector, "hello world", DataType.STRING, resp
        )
        assert got["documents"][0]["detectedLanguages"][0]["name"] == "English"
        assert "language" not in sent["documents"][0]  # contract: no lang field

    def test_entity_detector_and_key_phrases(self):
        from mmlspark_tpu.io.cognitive import EntityDetector, KeyPhraseExtractor

        resp = {"documents": [{"id": "1", "entities": [{"name": "Seattle"}]}]}
        got, _, sent = self._run(
            EntityDetector, "I live in Seattle", DataType.STRING, resp
        )
        assert got["documents"][0]["entities"][0]["name"] == "Seattle"
        assert sent["documents"][0]["language"] == "en"

        resp = {"documents": [{"id": "1", "keyPhrases": ["wonderful trip"]}]}
        got, _, sent = self._run(
            KeyPhraseExtractor, "it was a wonderful trip", DataType.STRING, resp
        )
        assert got["documents"][0]["keyPhrases"] == ["wonderful trip"]

    def test_ocr_query_params(self):
        from mmlspark_tpu.io.cognitive import OCR

        resp = {"language": "en", "regions": [{"lines": []}]}
        got, path, sent = self._run(
            OCR, "http://img.example/1.png", DataType.STRING, resp,
            language="en",
        )
        assert "language=en" in path and "detectOrientation=true" in path
        assert sent == {"url": "http://img.example/1.png"}
        assert got["regions"] == [{"lines": []}]

    def test_analyze_image(self):
        from mmlspark_tpu.io.cognitive import AnalyzeImage

        resp = {"categories": [{"name": "outdoor", "score": 0.9}]}
        got, path, sent = self._run(
            AnalyzeImage, "http://img.example/2.png", DataType.STRING, resp,
            visual_features=["Categories", "Tags"],
        )
        assert "visualFeatures=Categories%2CTags" in path
        assert got["categories"][0]["name"] == "outdoor"

    def test_generate_thumbnails(self):
        from mmlspark_tpu.io.cognitive import GenerateThumbnails

        got, path, sent = self._run(
            GenerateThumbnails, "http://img.example/3.png", DataType.STRING,
            {"ok": True}, width=32, height=24,
        )
        assert "width=32" in path and "height=24" in path
        assert "smartCropping=true" in path

    def test_detect_face(self):
        from mmlspark_tpu.io.cognitive import DetectFace

        resp = {"value": [{"faceId": "abc", "faceRectangle": {"top": 1}}]}
        got, path, sent = self._run(
            DetectFace, "http://img.example/4.png", DataType.STRING, resp,
            return_face_attributes=["age", "gender"],
        )
        assert "returnFaceId=true" in path
        assert "returnFaceAttributes=age%2Cgender" in path
        assert got["value"][0]["faceId"] == "abc"

    def test_verify_faces(self):
        from mmlspark_tpu.io.cognitive import VerifyFaces

        resp = {"isIdentical": True, "confidence": 0.93}
        got, _, sent = self._run(
            VerifyFaces, ["id1", "id2"], DataType.STRUCT, resp,
        )
        assert sent == {"faceId1": "id1", "faceId2": "id2"}
        assert got["isIdentical"] is True


class TestAzureSearch:
    INDEX = json.dumps({
        "name": "test-index",
        "fields": [
            {"name": "id", "type": "Edm.String", "key": True},
            {"name": "text", "type": "Edm.String"},
        ],
    })

    def _server(self, index_exists=False):
        """Mock speaking the index contract: GET probe (404 unless exists),
        POST /indexes creation, POST docs/index uploads."""
        import http.server

        captured = {"created": [], "uploads": [], "probes": 0}

        class H(http.server.BaseHTTPRequestHandler):
            def _reply(self, code, payload=b"{}"):
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                captured["probes"] += 1
                self._reply(200 if index_exists else 404)

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n))
                if self.path.startswith("/indexes?"):
                    captured["created"].append(body)
                    self._reply(201)
                else:
                    captured["uploads"].append(
                        (self.headers.get("api-key"), body)
                    )
                    self._reply(200)

            def log_message(self, *a):
                pass

        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return httpd, captured

    def test_write_creates_index_and_uploads(self):
        from mmlspark_tpu.io import azure_search

        httpd, captured = self._server()
        try:
            base = f"http://127.0.0.1:{httpd.server_address[1]}"
            df = DataFrame.from_dict(
                {"id": np.array(["1", "2", "3"], object),
                 "text": np.array(["a", "b", "c"], object)},
                types={"id": DataType.STRING, "text": DataType.STRING},
            )
            sent = azure_search.write(df, base, self.INDEX, key="admin-key",
                                      batch_size=2)
            assert sent == 2  # 2 + 1
            assert captured["created"][0]["name"] == "test-index"
            key, batch = captured["uploads"][0]
            assert key == "admin-key"
            assert batch["value"][0]["@search.action"] == "upload"
            assert batch["value"][0]["id"] == "1"
        finally:
            httpd.shutdown()

    def test_existing_index_not_recreated(self):
        from mmlspark_tpu.io import azure_search

        httpd, captured = self._server(index_exists=True)
        try:
            base = f"http://127.0.0.1:{httpd.server_address[1]}"
            created = azure_search.create_index_if_missing(
                base, self.INDEX, key="k"
            )
            assert created is False
            assert captured["created"] == []
        finally:
            httpd.shutdown()

    def test_schema_parity_enforced(self):
        from mmlspark_tpu.io import azure_search

        df = DataFrame.from_dict({"bogus": np.arange(2.0)})
        with pytest.raises(ValueError, match="not fields of index"):
            azure_search.write(df, "http://unused", self.INDEX)

    def test_per_row_action_col(self):
        from mmlspark_tpu.io import azure_search

        httpd, captured = self._server()
        try:
            base = f"http://127.0.0.1:{httpd.server_address[1]}"
            df = DataFrame.from_dict(
                {"id": np.array(["1", "2"], object),
                 "text": np.array(["a", "b"], object),
                 "act": np.array(["upload", "delete"], object)},
                types={"id": DataType.STRING, "text": DataType.STRING,
                       "act": DataType.STRING},
            )
            azure_search.write(df, base, self.INDEX, action_col="act")
            _, batch = captured["uploads"][0]
            assert [d["@search.action"] for d in batch["value"]] == [
                "upload", "delete"]
            assert "act" not in batch["value"][0]
        finally:
            httpd.shutdown()


class TestImageSearch:
    def test_bing_image_search_get_contract(self):
        """BingImageSearch issues a GET with q/count/mkt query params and
        parses the {value: [...]} response (ImageSearch.scala:63)."""
        import http.server

        from mmlspark_tpu.io.cognitive import BingImageSearch

        captured = []
        body = json.dumps(
            {"value": [{"contentUrl": "http://img/1.png"},
                       {"contentUrl": "http://img/2.png"}]}
        ).encode()

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                captured.append((self.path, dict(self.headers)))
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}/images/search"
            df = DataFrame.from_dict(
                {"q": np.array(["red car"], object)},
                types={"q": DataType.STRING},
            )
            stage = BingImageSearch(
                url=url, subscription_key="k", input_col="q",
                output_col="results", count=2,
            )
            out = stage.transform(df)
            path, headers = captured[0]
            assert "q=red+car" in path and "count=2" in path and "mkt=en-US" in path
            assert headers.get("Ocp-Apim-Subscription-Key") == "k"
            urls = BingImageSearch.content_urls(out["results"][0])
            assert urls == ["http://img/1.png", "http://img/2.png"]
        finally:
            httpd.shutdown()
