"""Tests: FluentAPI sugar, udfs, PowerBI sink, cognitive-style clients."""

import json
import threading

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame, DataType
from mmlspark_tpu.io import powerbi
from mmlspark_tpu.io.cognitive import AnomalyDetector, TextSentiment
from mmlspark_tpu.stages.basic import UDFTransformer
from mmlspark_tpu.stages.udfs import get_value_at, get_value_at_column


class TestFluentAPI:
    def test_ml_transform_chains(self):
        from mmlspark_tpu.stages.basic import DropColumns, RenameColumn

        df = DataFrame.from_dict({"a": [1.0, 2.0], "b": [3.0, 4.0]})
        out = df.ml_transform(
            RenameColumn(input_col="a", output_col="a2"),
            DropColumns(cols=["b"]),
        )
        assert out.columns == ["a2"]

    def test_ml_fit(self):
        from mmlspark_tpu.stages.basic import ClassBalancer

        df = DataFrame.from_dict({"label": np.array([0.0, 0.0, 1.0])})
        model = df.ml_fit(ClassBalancer(input_col="label"))
        assert model.transform(df)["weight"][2] == 2.0


class TestUdfs:
    def test_get_value_at(self):
        df = DataFrame.from_dict({"v": np.arange(12.0).reshape(4, 3)})
        stage = UDFTransformer(input_col="v", output_col="second",
                               udf=get_value_at(1))
        out = stage.transform(df)
        np.testing.assert_allclose(out["second"], [1.0, 4.0, 7.0, 10.0])

    def test_get_value_at_column(self):
        vals = np.arange(6.0).reshape(3, 2)
        np.testing.assert_allclose(get_value_at_column(vals, 0), [0, 2, 4])


def _start_capture_server(status=200, body=b"{}"):
    """Tiny HTTP server that records JSON request bodies."""
    import http.server

    captured = []

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            captured.append(
                (self.path, dict(self.headers), self.rfile.read(n))
            )
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, captured


class TestPowerBI:
    def test_write_batches(self):
        httpd, captured = _start_capture_server()
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}/push"
            df = DataFrame.from_dict(
                {"name": np.array(list("abcde"), object), "x": np.arange(5.0)},
                types={"name": DataType.STRING},
            )
            sent = powerbi.write(df, url, {"batchSize": 2})
            assert sent == 3  # 2+2+1
            rows = [r for _, _, b in captured for r in json.loads(b)]
            assert len(rows) == 5
            assert {"name": "a", "x": 0.0} in rows
        finally:
            httpd.shutdown()

    def test_http_error_raises(self):
        httpd, _ = _start_capture_server(status=503)
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}/push"
            df = DataFrame.from_dict({"x": np.arange(3.0)})
            with pytest.raises(RuntimeError, match="HTTP 503"):
                powerbi.write(df, url, {"batchSize": 3})
        finally:
            httpd.shutdown()

    def test_rejects_unknown_option(self):
        df = DataFrame.from_dict({"x": np.arange(2.0)})
        with pytest.raises(ValueError, match="not applicable"):
            powerbi.write(df, "http://x", {"bogus": "1"})


class TestCognitive:
    def test_text_sentiment_contract(self):
        httpd, captured = _start_capture_server(
            body=json.dumps(
                {"documents": [{"id": "1", "score": 0.9}], "errors": []}
            ).encode()
        )
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}/sentiment"
            df = DataFrame.from_dict(
                {"text": np.array(["great product", "terrible"], object)},
                types={"text": DataType.STRING},
            )
            ts = TextSentiment(
                url=url, subscription_key="secret-key",
                input_col="text", output_col="sentiment",
            )
            out = ts.transform(df)
            assert "sentiment" in out.columns
            got = out["sentiment"][0]
            assert got["documents"][0]["score"] == 0.9
            # request contract: documents JSON + key header
            path, headers, body = captured[0]
            sent = json.loads(body)
            assert sent["documents"][0]["text"] == "great product"
            assert sent["documents"][0]["language"] == "en"
            assert headers.get("Ocp-Apim-Subscription-Key") == "secret-key"
        finally:
            httpd.shutdown()

    def test_anomaly_detector_body(self):
        httpd, captured = _start_capture_server(
            body=json.dumps({"isAnomaly": [False, True]}).encode()
        )
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}/anomaly"
            series = np.empty(1, object)
            series[0] = [
                {"timestamp": "2026-01-01T00:00:00Z", "value": 1.0},
                {"timestamp": "2026-01-02T00:00:00Z", "value": 99.0},
            ]
            df = DataFrame.from_dict({"series": series})
            ad = AnomalyDetector(url=url, input_col="series", output_col="verdict")
            out = ad.transform(df)
            assert out["verdict"][0]["isAnomaly"] == [False, True]
            sent = json.loads(captured[0][2])
            assert sent["granularity"] == "daily"
            assert len(sent["series"]) == 2
        finally:
            httpd.shutdown()
