"""Targeted regression tests for round-1 advisor/verdict findings
(ADVICE.md items 1-4; VERDICT.md weak spots 4, 5, 10)."""

import numpy as np
import pytest

from mmlspark_tpu.core import serialize
from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType, concat
from mmlspark_tpu.core.params import ComplexParam, Param, Params, TypeConverters
from mmlspark_tpu.core.pipeline import Transformer


class _RequiredArgStage(Transformer):
    alpha = Param("alpha", "a float", TypeConverters.to_float)
    blob = ComplexParam("blob", "an array")

    def __init__(self, required_thing):
        super().__init__()
        self.required_thing = required_thing
        self._set_defaults(alpha=0.5)

    def _init_args(self):
        # ConstructorWritable protocol (reference: ConstructorWriter.scala)
        return {"required_thing": self.required_thing}

    def transform(self, df):
        return df


class _AnyParamStage(Transformer):
    p = Param("p", "anything", TypeConverters.identity)

    def transform(self, df):
        return df


class _NoProtocolStage(Transformer):
    alpha = Param("alpha", "a float", TypeConverters.to_float)

    def __init__(self, required_thing):
        super().__init__()
        self.required_thing = required_thing
        self._set_defaults(alpha=0.25)

    def transform(self, df):
        return df


def test_drop_na_vector_rows():
    df = DataFrame.from_dict({"v": [[1.0, 2.0], [np.nan, 3.0], [4.0, 5.0]]})
    assert df.dtype("v") == DataType.VECTOR
    out = df.drop_na()
    assert len(out) == 2
    np.testing.assert_allclose(out["v"], [[1.0, 2.0], [4.0, 5.0]])


def test_outer_join_string_column_nulls():
    left = DataFrame.from_dict({"k": np.array([1, 2]), "s": np.array(["a", "b"])})
    right = DataFrame.from_dict({"k": np.array([2, 3]), "t": np.array(["x", "y"])})
    out = left.join(right, on="k", how="outer")
    rows = {r["k"]: r for r in out.collect()}
    assert rows[1]["t"] is None
    assert rows[3]["s"] is None
    assert rows[2]["t"] == "x"


def test_outer_join_int_column_becomes_nan_not_garbage():
    left = DataFrame.from_dict({"k": [1, 2], "x": np.array([10, 20], dtype=np.int64)})
    right = DataFrame.from_dict({"k": [2, 3], "y": np.array([7, 8], dtype=np.int64)})
    out = left.join(right, on="k", how="outer")
    rows = {r["k"]: r for r in out.collect()}
    assert np.isnan(rows[1]["y"])
    assert rows[2]["y"] == 7


def test_concat_linear_and_typed():
    frames = [DataFrame.from_dict({"a": [i, i + 1]}) for i in range(5)]
    out = concat(frames)
    assert len(out) == 10
    assert out["a"][0] == 0 and out["a"][-1] == 5


def test_map_partitions_preserves_rows():
    df = DataFrame.from_dict({"a": list(range(100))}, num_partitions=7)
    out = df.map_partitions(lambda p: p)
    assert len(out) == 100
    np.testing.assert_array_equal(out["a"], np.arange(100))


def test_serialize_constructor_writable_roundtrip(tmp_path):
    stage = _RequiredArgStage(required_thing="hello")
    stage.set("blob", np.arange(3))
    path = str(tmp_path / "stage")
    stage.save(path)
    loaded = serialize.load_stage(path)
    # __init__ re-ran with the persisted constructor args
    assert loaded.required_thing == "hello"
    assert loaded.get("alpha") == 0.5
    np.testing.assert_array_equal(loaded.get("blob"), np.arange(3))


def test_serialize_restores_defaults_without_protocol(tmp_path):
    stage = _NoProtocolStage(required_thing="x")
    path = str(tmp_path / "stage")
    stage.save(path)
    loaded = serialize.load_stage(path)
    # __init__ could not re-run (required arg, no protocol) but the default
    # param map survived via metadata.
    assert loaded.get("alpha") == 0.25


def test_failed_save_preserves_previous_good_save(tmp_path):
    path = str(tmp_path / "s")
    good = _AnyParamStage().set("p", 1)
    good.save(path)
    bad = _AnyParamStage().set("p", object())
    with pytest.raises(TypeError):
        bad.save(path, overwrite=True)
    loaded = serialize.load_stage(path)  # old save intact
    assert loaded.get("p") == 1


def test_simple_param_non_json_fails_loudly(tmp_path):
    s = _AnyParamStage()
    s.set("p", object())
    with pytest.raises(TypeError, match="ComplexParam"):
        s.save(str(tmp_path / "s"))


def test_make_mesh_rejects_mismatched_shape():
    from mmlspark_tpu.core.env import make_mesh

    with pytest.raises(ValueError, match="devices"):
        make_mesh(shape=(3,))  # 8 virtual devices in tests


def test_make_mesh_explicit_devices_subset():
    import jax

    from mmlspark_tpu.core.env import make_mesh

    mesh = make_mesh(shape=(4,), devices=jax.devices()[:4])
    assert mesh.devices.shape == (4,)
