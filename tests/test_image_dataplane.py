"""ISSUE 7 acceptance: the device-resident image dataplane.

- fused device prep (images/device_ops.py) matches the numpy oracle
  (images/ops.py) within ±1 uint8 LSB per op (resize/crop/flip/color) and
  1e-5 (normalize/unroll) on randomized property tests;
- a decode -> fused-prep -> TPUModel -> select chain performs EXACTLY one
  h2d per batch and zero d2h before the final read (dataplane counters +
  jax.transfer_guard, same belt-and-braces as tests/test_dataplane.py);
- the double-buffered prefetcher (core/prefetch.py) overlaps batch N+1's
  host decode + upload with batch N's consumer compute, measured through
  its timeline and the dataplane counters on a fake-slow decoder;
- zoo bf16 inference variants match f32 top-1 with relative logit MAE
  under the documented BF16_LOGIT_MAE_TOL; dtype="float32" stays default;
- the batched host fallbacks (ops.resize_groups, ops.unroll) match the
  per-row path exactly;
- ImageServingHandler stages image requests through the fused path with
  parse-stage uploads and per-row 400s for undecodable rows.
"""

import io
import json
import time

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType
from mmlspark_tpu.core.schema import make_image_row
from mmlspark_tpu.images import ops
from mmlspark_tpu.images import device_ops
from mmlspark_tpu.utils.profiling import dataplane_counters


def _rand_batch(rng, n=5, h=19, w=23, c=3):
    return rng.integers(0, 256, (n, h, w, c), dtype=np.uint8)


def _npy_bytes(img):
    buf = io.BytesIO()
    np.save(buf, img)
    return buf.getvalue()


# -- fused op parity vs the numpy oracle --------------------------------------


class TestFusedOpParity:
    """Randomized property tests: each device op vs its oracle, ±1 uint8
    LSB for integer-valued ops, 1e-5 for the float-valued terminals."""

    @pytest.mark.parametrize("seed", [0, 1, 2])
    @pytest.mark.parametrize(
        "hw,out_hw", [((19, 23), (8, 8)), ((10, 14), (28, 21)), ((9, 9), (9, 9))]
    )
    def test_resize(self, seed, hw, out_hw):
        rng = np.random.default_rng(seed)
        batch = _rand_batch(rng, h=hw[0], w=hw[1])
        st = {"op": "resize", "height": out_hw[0], "width": out_hw[1]}
        fused = device_ops.fused_prep_program([st], unroll=False)
        got = np.asarray(fused(batch))
        want = np.stack([ops.resize(im, *out_hw) for im in batch])
        assert np.abs(got - want.astype(np.float64)).max() <= 1.0

    @pytest.mark.parametrize("seed", [0, 1])
    def test_crop(self, seed):
        rng = np.random.default_rng(seed)
        batch = _rand_batch(rng)
        st = {"op": "crop", "x": 3, "y": 2, "height": 7, "width": 11}
        got = np.asarray(device_ops.fused_prep_program([st], unroll=False)(batch))
        want = np.stack([ops.crop(im, 3, 2, 7, 11) for im in batch])
        np.testing.assert_array_equal(got, want.astype(np.float64))

    def test_crop_out_of_bounds_raises(self):
        st = {"op": "crop", "x": 20, "y": 0, "height": 7, "width": 11}
        batch = _rand_batch(np.random.default_rng(0))
        with pytest.raises(ValueError, match="outside image"):
            device_ops.fused_prep_program([st], unroll=False)(batch)

    @pytest.mark.parametrize("code", [0, 1, -1])
    def test_flip(self, code):
        batch = _rand_batch(np.random.default_rng(3))
        st = {"op": "flip", "flip_code": code}
        got = np.asarray(device_ops.fused_prep_program([st], unroll=False)(batch))
        want = np.stack([ops.flip(im, code) for im in batch])
        np.testing.assert_array_equal(got, want.astype(np.float64))

    @pytest.mark.parametrize("fmt", ["gray", "rgb", "bgr"])
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_colorformat(self, fmt, seed):
        batch = _rand_batch(np.random.default_rng(seed))
        st = {"op": "colorformat", "format": fmt}
        got = np.asarray(device_ops.fused_prep_program([st], unroll=False)(batch))
        want = np.stack([ops.color_format(im, fmt) for im in batch])
        if want.ndim == 3:
            want = want[:, :, :, None]
        assert np.abs(got - want.astype(np.float64)).max() <= 1.0

    def test_normalize(self):
        batch = _rand_batch(np.random.default_rng(4))
        mean, std, scale = [0.45, 0.4, 0.5], [0.2, 0.25, 0.3], 1 / 255.0
        st = {"op": "normalize", "mean": mean, "std": std,
              "color_scale_factor": scale}
        got = np.asarray(device_ops.fused_prep_program([st], unroll=False)(batch))
        want = np.stack([ops.normalize(im, mean, std, scale) for im in batch])
        assert np.abs(got - want).max() <= 1e-5

    def test_unroll(self):
        batch = _rand_batch(np.random.default_rng(5))
        got = np.asarray(device_ops.fused_prep_program([], unroll=True)(batch))
        assert np.abs(got - ops.unroll(batch)).max() <= 1e-5

    def test_chain_quantizes_between_stages(self):
        """A resize->flip->gray->normalize chain matches the per-row oracle
        applied stage by stage (each uint8 stage re-quantized, as the
        oracle does): the ±1 LSB per-op bound compounds to at most 2 LSB
        pre-normalize, scaled by color_scale_factor/std after."""
        rng = np.random.default_rng(6)
        batch = _rand_batch(rng, n=4, h=25, w=17)
        scale, std = 1 / 255.0, 0.3
        stages = [
            {"op": "resize", "height": 12, "width": 12},
            {"op": "flip", "flip_code": 1},
            {"op": "colorformat", "format": "gray"},
            {"op": "normalize", "mean": [0.4], "std": [std],
             "color_scale_factor": scale},
        ]
        got = np.asarray(device_ops.fused_prep_program(stages, unroll=True)(batch))

        def oracle(im):
            x = ops.resize(im, 12, 12)
            x = ops.flip(x, 1)
            x = ops.color_format(x, "gray")
            return ops.normalize(x, [0.4], [std], scale)

        want = ops.unroll(np.stack([oracle(im) for im in batch]))
        assert np.abs(got - want).max() <= 2 * scale / std + 1e-5

    def test_flat_input_folds_unflatten(self):
        """Serving shape: flat (N, H*W*C) uint8 vectors un-flatten inside
        the same program (in_shape=...), no separate reshape dispatch."""
        batch = _rand_batch(np.random.default_rng(7), h=8, w=8)
        flat = batch.reshape(len(batch), -1)
        st = {"op": "resize", "height": 4, "width": 4}
        got = np.asarray(
            device_ops.fused_prep_program([st], unroll=True, in_shape=(8, 8, 3))(flat)
        )
        want = ops.unroll(ops.resize_batch(batch, 4, 4))
        assert np.abs(got - want).max() <= 1.0

    def test_unsupported_op_refused(self):
        with pytest.raises(ValueError, match="no device implementation"):
            device_ops.fused_prep_program(
                [{"op": "blur", "height": 3, "width": 3}]
            )

    def test_max_rows_chunks_large_batches(self):
        """A batch over max_rows stages in bounded chunks — ceil(n/max_rows)
        uploads sharing ONE program shape (last chunk pads) — and the
        concatenated device result matches the unchunked output exactly."""
        rng = np.random.default_rng(11)
        arrays = [
            rng.integers(0, 256, (10, 10, 3), dtype=np.uint8) for _ in range(11)
        ]
        whole, meta_w = device_ops.fused_unrolled_batch(arrays, size=(6, 6))
        before = dataplane_counters().snapshot()
        chunked, meta_c = device_ops.fused_unrolled_batch(
            arrays, size=(6, 6), max_rows=4
        )
        delta = dataplane_counters().delta(before)
        assert delta["h2d_transfers"] == 3, delta  # ceil(11/4)
        assert meta_c == meta_w
        assert chunked.shape[0] == 11
        assert np.array_equal(np.asarray(chunked), np.asarray(whole))

    def test_pad_to_bucket_reuses_programs_across_sizes(self):
        """The serving shape: distinct batch sizes inside one power-of-two
        bucket share a compiled program (pad + compiled trim), so the
        coalescer's ragged Ns don't trace per exact size."""
        rng = np.random.default_rng(12)

        def run(n):
            arrays = [
                rng.integers(0, 256, (6, 6, 3), dtype=np.uint8)
                for _ in range(n)
            ]
            dev, _ = device_ops.fused_unrolled_batch(
                arrays, size=(6, 6), pad_to_bucket=True
            )
            assert dev.shape == (n, 6 * 6 * 3)
            # rows beyond n were pad copies and must be gone after trim
            want = ops.unroll(np.stack(arrays))
            assert np.abs(np.asarray(dev) - want).max() <= 1e-5
            return dev

        run(5)  # bucket 8: compile
        before = dataplane_counters().snapshot()
        run(6)  # same bucket: no new compile
        run(7)
        assert dataplane_counters().delta(before)["compiles"] == 0

    def test_chain_out_shape(self):
        stages = [
            {"op": "resize", "height": 12, "width": 10},
            {"op": "colorformat", "format": "gray"},
        ]
        assert device_ops.chain_out_shape(stages, (30, 30, 3)) == (12, 10, 1)
        assert device_ops.supported_chain(stages)
        assert not device_ops.supported_chain([{"op": "blur"}])


# -- batched host fallbacks ----------------------------------------------------


class TestHostBatchFallbacks:
    def test_resize_groups_matches_per_row(self):
        rng = np.random.default_rng(0)
        imgs = (
            [rng.integers(0, 256, (16, 12, 3), dtype=np.uint8) for _ in range(3)]
            + [rng.integers(0, 256, (9, 9, 3), dtype=np.uint8) for _ in range(2)]
            + [rng.integers(0, 256, (16, 12, 3), dtype=np.uint8)]
        )
        out = ops.resize_groups(imgs, 8, 8)
        for im, o in zip(imgs, out):
            np.testing.assert_array_equal(o, ops.resize(im, 8, 8))

    def test_host_unroll_oracle_matches_transformer(self):
        from mmlspark_tpu.images import UnrollImage

        rng = np.random.default_rng(1)
        imgs = rng.integers(0, 256, (4, 6, 5, 3), dtype=np.uint8)
        rows = np.empty(4, object)
        for i, im in enumerate(imgs):
            rows[i] = make_image_row(im, f"i{i}")
        df = DataFrame({"image": Column(rows, DataType.STRUCT)})
        host = UnrollImage("image", "vec").transform(df)["vec"]
        np.testing.assert_allclose(host, ops.unroll(imgs))

    def test_unroll_image_to_device(self):
        """UnrollImage(to_device=True) emits a device-backed column whose
        lazy host sync equals the host unroll."""
        from mmlspark_tpu.images import UnrollImage

        rng = np.random.default_rng(2)
        imgs = rng.integers(0, 256, (3, 5, 7, 3), dtype=np.uint8)
        rows = np.empty(3, object)
        for i, im in enumerate(imgs):
            rows[i] = make_image_row(im, f"i{i}")
        df = DataFrame({"image": Column(rows, DataType.STRUCT)})
        out = UnrollImage("image", "vec", to_device=True).transform(df)
        col = out.column("vec")
        assert col.is_device_backed
        assert col.metadata["unrolled"]["order"] == "CHW"
        np.testing.assert_allclose(col.values, ops.unroll(imgs), atol=1e-5)


# -- the one-upload chain guarantee -------------------------------------------


def _mini_bundle(h=8, w=8):
    import jax

    from mmlspark_tpu.dnn import resnet_mini
    from mmlspark_tpu.dnn.network import NetworkBundle

    net = resnet_mini(num_classes=4, input_shape=(h, w, 3))
    return NetworkBundle(net, net.init(jax.random.PRNGKey(0)))


class TestOneUploadChain:
    def test_decode_fused_prep_model_select_one_h2d_zero_d2h(self):
        """The acceptance chain: BINARY decode -> fused prep -> TPUModel ->
        select performs EXACTLY one h2d for the whole batch and zero d2h
        until the final read (which costs exactly one). transfer_guard
        ("disallow") catches implicit transfers the counters can't see."""
        import jax

        from mmlspark_tpu.images import ImageFeaturizer

        counters = dataplane_counters()
        rng = np.random.default_rng(0)
        imgs = rng.integers(0, 256, (6, 14, 14, 3), dtype=np.uint8)
        blobs = np.empty(6, object)
        blobs[:] = [_npy_bytes(im) for im in imgs]
        df = DataFrame({"raw": Column(blobs, DataType.BINARY)})

        feat = ImageFeaturizer(
            model=_mini_bundle(), input_col="raw", output_col="features",
            cut_output_layers=1,
        )
        feat.transform(df)  # warm: compiles + the one-time weight upload

        before = counters.snapshot()
        with jax.transfer_guard("disallow"):
            out = feat.transform(df).select("features")
        delta = counters.delta(before)
        assert delta["h2d_transfers"] == 1, delta
        assert delta["d2h_transfers"] == 0, delta
        assert out.column("features").is_device_backed

        # the final read is the chain's single d2h
        before = counters.snapshot()
        vals = out["features"]
        delta = counters.delta(before)
        assert delta["d2h_transfers"] == 1 and delta["h2d_transfers"] == 0
        assert vals.shape == (6, 8)

    def test_struct_fused_prep_matches_host_prep(self):
        """fused=True (one upload + one XLA program) and fused=False (the
        per-row host path) produce the same features: same-size inputs make
        the prep an exact identity in both paths."""
        from mmlspark_tpu.images import ImageFeaturizer

        rng = np.random.default_rng(1)
        imgs = rng.integers(0, 256, (5, 8, 8, 3), dtype=np.uint8)
        rows = np.empty(5, object)
        for i, im in enumerate(imgs):
            rows[i] = make_image_row(im, f"i{i}")
        df = DataFrame({"image": Column(rows, DataType.STRUCT)})
        bundle = _mini_bundle()

        def run(fused):
            f = ImageFeaturizer(model=bundle, input_col="image",
                                output_col="features", cut_output_layers=1)
            f.set_fused(fused)
            return np.asarray(f.transform(df)["features"])

        np.testing.assert_allclose(run(True), run(False), atol=1e-4)

    def test_ragged_struct_prep_groups_by_shape(self):
        """Ragged source shapes still take the batched path: grouped host
        resize + device unroll, same features as the host path within the
        resize f32-vs-f64 LSB bound propagated through the net."""
        from mmlspark_tpu.images import ImageFeaturizer

        rng = np.random.default_rng(2)
        shapes = [(12, 9, 3), (16, 16, 3), (12, 9, 3), (10, 11, 3)]
        rows = np.empty(len(shapes), object)
        for i, s in enumerate(shapes):
            rows[i] = make_image_row(
                rng.integers(0, 256, s).astype(np.uint8), f"i{i}"
            )
        df = DataFrame({"image": Column(rows, DataType.STRUCT)})
        bundle = _mini_bundle()

        def run(fused):
            f = ImageFeaturizer(model=bundle, input_col="image",
                                output_col="features", cut_output_layers=1)
            f.set_fused(fused)
            return np.asarray(f.transform(df)["features"])

        got, want = run(True), run(False)
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, atol=5e-2)

    def test_fused_prep_falls_back_on_nulls(self):
        from mmlspark_tpu.images import ImageFeaturizer

        rng = np.random.default_rng(3)
        rows = np.empty(3, object)
        for i in range(3):
            rows[i] = make_image_row(
                rng.integers(0, 256, (8, 8, 3)).astype(np.uint8), f"i{i}"
            )
        rows[1] = None
        df = DataFrame({"image": Column(rows, DataType.STRUCT)})
        feat = ImageFeaturizer(model=_mini_bundle(), input_col="image",
                               output_col="features", cut_output_layers=1)
        feat.set(feat.drop_na, False)  # keep the null: stacking must bail
        with pytest.raises((ValueError, TypeError)):
            # the fused path bails to the host path's own null handling
            # (UnrollImage refuses nulls), not a device crash
            feat.transform(df)


# -- double-buffered prefetch --------------------------------------------------


class TestPrefetch:
    def test_overlap_with_fake_slow_decoder(self):
        """Batch N+1's decode+upload completes while the consumer computes
        batch N: measured by the prefetcher's own timeline (upload_done
        before the consumer asked) and the counters' per-batch uploads."""
        from mmlspark_tpu.core.prefetch import DeviceBatchPrefetcher

        counters = dataplane_counters()
        items = list(range(24))

        def decode(chunk):  # fake-slow host decode: 5 ms per batch
            time.sleep(0.005)
            return np.full((len(chunk), 16), float(chunk[0]), np.float32)

        before = counters.snapshot()
        pf = DeviceBatchPrefetcher(items, decode, batch_size=4, depth=2)
        seen = []
        with pf:
            for batch in pf:
                seen.append(np.asarray(batch)[0, 0])
                time.sleep(0.02)  # consumer compute, slower than prep
        s = pf.summary()
        assert s["batches"] == 6
        assert seen == [0.0, 4.0, 8.0, 12.0, 16.0, 20.0]
        # every batch after the first staged entirely behind the consumer
        assert s["overlapped_batches"] >= 4, s
        assert s["overlap_ratio"] >= 0.5, s
        # the proof the ISSUE asks for: upload of batch N+1 finished before
        # the consumer came back from computing batch N
        tl = pf.timeline()
        assert any(
            e["index"] > 0 and 0 <= e["upload_done_t"] <= e["requested_t"]
            for e in tl
        ), tl
        # uploads are per-batch and counted in the shared meters
        delta = counters.delta(before)
        assert delta["h2d_transfers"] == 6, delta

    def test_decode_error_surfaces_to_consumer(self):
        from mmlspark_tpu.core.prefetch import DeviceBatchPrefetcher

        def decode(chunk):
            if chunk[0] >= 4:
                raise RuntimeError("corrupt shard")
            return np.zeros((len(chunk), 2), np.float32)

        pf = DeviceBatchPrefetcher(list(range(8)), decode, batch_size=4)
        with pytest.raises(RuntimeError, match="corrupt shard"):
            with pf:
                for _ in pf:
                    pass

    def test_early_exit_cleanup(self):
        from mmlspark_tpu.core.prefetch import DeviceBatchPrefetcher

        def decode(chunk):
            return np.zeros((len(chunk), 2), np.float32)

        pf = DeviceBatchPrefetcher(list(range(64)), decode, batch_size=4)
        with pf:
            next(iter(pf))
        pf.close()
        assert not pf._thread.is_alive()

    def test_close_unblocks_parked_consumer(self):
        """close() from another thread while the consumer is blocked in
        __next__ on an empty queue must end the iteration, not deadlock
        (regression: the producer's finally used to skip the sentinel
        whenever stop was already set)."""
        import threading

        from mmlspark_tpu.core.prefetch import DeviceBatchPrefetcher

        release = threading.Event()

        def decode(chunk):  # stalls until the closer has fired
            release.wait(timeout=5.0)
            return np.zeros((len(chunk), 2), np.float32)

        pf = DeviceBatchPrefetcher(list(range(8)), decode, batch_size=4)

        def closer():
            time.sleep(0.05)  # let the consumer park in q.get() first
            pf.close()
            release.set()

        t = threading.Thread(target=closer)
        t.start()
        got = list(pf)  # must return (empty), not hang
        t.join()
        assert got == []
        assert not pf._thread.is_alive()

    def test_abandoned_prefetcher_self_terminates(self):
        """Dropping the object (no close()) stops the pipeline via the
        weakref finalizer — a consumer that breaks out of a bare for loop
        cannot strand a producer pinning device batches."""
        import gc

        from mmlspark_tpu.core.prefetch import DeviceBatchPrefetcher

        def decode(chunk):
            return np.zeros((len(chunk), 2), np.float32)

        pf = DeviceBatchPrefetcher(list(range(256)), decode, batch_size=4)
        next(iter(pf))
        thread = pf._thread
        state = pf._state
        del pf
        gc.collect()
        assert state.stop.wait(timeout=2.0)
        thread.join(timeout=5.0)
        assert not thread.is_alive()

    def test_host_only_mode(self):
        from mmlspark_tpu.core.prefetch import DeviceBatchPrefetcher

        def decode(chunk):
            return np.asarray(chunk, np.float32)

        with DeviceBatchPrefetcher(
            list(range(6)), decode, batch_size=3, upload=False
        ) as pf:
            batches = [b for b in pf]
        assert all(isinstance(b, np.ndarray) for b in batches)
        assert len(batches) == 2


# -- bf16 inference variants ---------------------------------------------------


class TestBf16Variants:
    def test_zoo_bf16_parity_gate(self):
        """The documented gate: bf16 scoring of a zoo model matches f32
        top-1 exactly and relative logit MAE stays under
        BF16_LOGIT_MAE_TOL. An unset dtype inherits the bundle network's
        own compute dtype (f32 here); dtype='float32' is the explicit
        rollback."""
        from mmlspark_tpu.dnn.zoo_builders import (
            BF16_LOGIT_MAE_TOL,
            bf16_variant,
            resnet50_random,
        )
        from mmlspark_tpu.models import TPUModel

        bundle = resnet50_random(num_classes=10, input_shape=(32, 32, 3))
        rng = np.random.default_rng(0)
        x = rng.integers(0, 256, (8, 32 * 32 * 3), dtype=np.uint8)
        df = DataFrame.from_dict({"features": x})

        default_model = TPUModel(bundle, input_col="features", output_col="o")
        assert default_model.get(default_model.dtype) == ""  # inherit
        assert default_model._network_for_eval().compute_dtype == "float32"
        # a bf16 zoo variant stays bf16 through the default (inherit) model
        inherit_bf16 = TPUModel(
            bf16_variant(bundle), input_col="features", output_col="o"
        )
        assert inherit_bf16._network_for_eval().compute_dtype == "bfloat16"
        # explicit float32 is the rollback even on a bf16 bundle
        forced = TPUModel(
            bf16_variant(bundle), input_col="features", output_col="o",
            dtype="float32",
        )
        assert forced._network_for_eval().compute_dtype == "float32"
        f32 = np.asarray(default_model.transform(df)["o"])
        bf16 = np.asarray(
            TPUModel(bundle, input_col="features", output_col="o",
                     dtype="bfloat16").transform(df)["o"]
        )
        assert bf16.dtype == np.float32  # output column stays f32
        rel_mae = np.abs(f32 - bf16).mean() / np.abs(f32).mean()
        assert rel_mae < BF16_LOGIT_MAE_TOL, rel_mae
        assert (f32.argmax(axis=1) == bf16.argmax(axis=1)).all()

    def test_bf16_variant_shares_variables(self):
        from mmlspark_tpu.dnn.zoo_builders import bf16_variant, resnet50_random

        bundle = resnet50_random(num_classes=4, input_shape=(16, 16, 3))
        twin = bf16_variant(bundle)
        assert twin.network.compute_dtype == "bfloat16"
        assert twin.variables is bundle.variables
        assert bf16_variant(twin) is twin  # idempotent
        # the builder's dtype kwarg produces the same thing directly
        direct = resnet50_random(
            num_classes=4, input_shape=(16, 16, 3), dtype="bfloat16"
        )
        assert direct.network.compute_dtype == "bfloat16"

    def test_featurizer_dtype_passthrough(self):
        from mmlspark_tpu.images import ImageFeaturizer

        rng = np.random.default_rng(1)
        rows = np.empty(4, object)
        for i in range(4):
            rows[i] = make_image_row(
                rng.integers(0, 256, (8, 8, 3)).astype(np.uint8), f"i{i}"
            )
        df = DataFrame({"image": Column(rows, DataType.STRUCT)})
        bundle = _mini_bundle()

        def feats(dtype):
            f = ImageFeaturizer(model=bundle, input_col="image",
                                output_col="features", cut_output_layers=1)
            f.set_dtype(dtype)
            return np.asarray(f.transform(df)["features"])

        f32, bf16 = feats("float32"), feats("bfloat16")
        assert f32.shape == bf16.shape
        denom = max(np.abs(f32).mean(), 1e-9)
        assert np.abs(f32 - bf16).mean() / denom < 5e-2


# -- int8 inference variants ---------------------------------------------------


class TestInt8Variants:
    def test_zoo_int8_parity_gate(self):
        """The documented gate, in the bf16 gate's shape: int8 weight-only
        scoring of a zoo model matches f32 top-1 EXACTLY and relative
        logit MAE stays under INT8_LOGIT_MAE_TOL. dtype='float32' on the
        f32 bundle remains the rollback."""
        from mmlspark_tpu.dnn.zoo_builders import (
            INT8_LOGIT_MAE_TOL,
            int8_variant,
            resnet50_random,
        )
        from mmlspark_tpu.models import TPUModel

        bundle = resnet50_random(num_classes=10, input_shape=(32, 32, 3))
        rng = np.random.default_rng(0)
        x = rng.integers(0, 256, (8, 32 * 32 * 3), dtype=np.uint8)
        df = DataFrame.from_dict({"features": x})

        # an int8 zoo variant stays int8 through the default (inherit)
        inherit = TPUModel(
            int8_variant(bundle), input_col="features", output_col="o"
        )
        assert inherit._network_for_eval().compute_dtype == "int8"
        # dtype="int8" on an f32 bundle quantizes at eval time (cached)
        quantized = TPUModel(bundle, input_col="features", output_col="o",
                             dtype="int8")
        assert quantized._network_for_eval().compute_dtype == "int8"

        f32 = np.asarray(
            TPUModel(bundle, input_col="features",
                     output_col="o").transform(df)["o"]
        )
        i8 = np.asarray(quantized.transform(df)["o"])
        assert i8.dtype == np.float32  # activations/output stay f32
        rel_mae = np.abs(f32 - i8).mean() / np.abs(f32).mean()
        assert rel_mae < INT8_LOGIT_MAE_TOL, rel_mae
        assert (f32.argmax(axis=1) == i8.argmax(axis=1)).all()

    def test_int8_variant_quantizes_kernels_only(self):
        from mmlspark_tpu.dnn.zoo_builders import int8_variant, resnet50_random

        bundle = resnet50_random(num_classes=4, input_shape=(16, 16, 3))
        twin = int8_variant(bundle)
        assert twin.network.compute_dtype == "int8"
        assert twin.variables is not bundle.variables  # codes, not shares
        assert int8_variant(twin) is twin  # idempotent
        # every conv/dense kernel is int8 + per-channel scale; BN untouched
        seen = []

        def walk(tree):
            for k, v in tree.items():
                if isinstance(v, dict):
                    walk(v)
                elif k == "kernel":
                    seen.append((np.asarray(v).dtype,
                                 "kernel_scale" in tree))
        walk(twin.variables["params"])
        assert seen and all(dt == np.int8 and has for dt, has in seen)
        # the builder's dtype kwarg produces the same thing directly
        direct = resnet50_random(
            num_classes=4, input_shape=(16, 16, 3), dtype="int8"
        )
        assert direct.network.compute_dtype == "int8"


# -- serving: the fused path behind the staged handler ------------------------


def _image_request_frame(payloads):
    from mmlspark_tpu.io.http import HTTPRequestData

    reqs = np.empty(len(payloads), object)
    reqs[:] = [
        HTTPRequestData.post_json("http://localhost/api", json.dumps(p))
        for p in payloads
    ]
    ids = np.empty(len(payloads), object)
    ids[:] = [{"requestId": str(i), "partitionId": 0} for i in range(len(payloads))]
    return DataFrame.from_dict(
        {"id": ids, "request": reqs},
        types={"id": DataType.STRUCT, "request": DataType.STRUCT},
    )


class TestImageServingHandler:
    def test_staged_image_scoring(self):
        import base64

        import jax

        from mmlspark_tpu.serving import ImageServingHandler

        bundle = _mini_bundle()
        handler = ImageServingHandler(bundle, value_col="scored")
        rng = np.random.default_rng(0)
        imgs = [
            rng.integers(0, 256, (8, 8, 3), dtype=np.uint8) for _ in range(3)
        ]
        payloads = [
            {"image": base64.b64encode(_npy_bytes(imgs[0])).decode()},
            {"pixels": imgs[1].tolist()},
            {"image": base64.b64encode(_npy_bytes(imgs[2])).decode()},
        ]
        frame = _image_request_frame(payloads)
        handler(frame)  # warm: compiles + weight upload

        parsed = handler.parse(frame)
        col = parsed.column("unrolled")
        assert col.is_device_backed  # the upload happened in parse
        np.testing.assert_allclose(
            col.values, ops.unroll(np.stack(imgs)), atol=1e-5
        )
        # score is dispatch-only: transfer-free under the guard
        with jax.transfer_guard("disallow"):
            scored = handler.score(parsed)
        replies = handler.reply(scored)["reply"]
        for r in replies:
            assert r.status_line.status_code == 200
            assert len(json.loads(bytes(r.entity.content))) == 4

    def test_ragged_and_malformed_rows(self):
        import base64

        from mmlspark_tpu.serving import ImageServingHandler

        bundle = _mini_bundle()
        handler = ImageServingHandler(bundle, value_col="scored")
        rng = np.random.default_rng(1)
        payloads = [
            {"pixels": rng.integers(0, 256, (12, 10, 3)).tolist()},  # ragged
            {"image": base64.b64encode(b"not an image").decode()},   # bad
            {"pixels": rng.integers(0, 256, (8, 8, 3)).tolist()},    # exact
            {"wrong_key": 1},                                        # bad
        ]
        replies = handler(_image_request_frame(payloads))["reply"]
        codes = [r.status_line.status_code for r in replies]
        assert codes == [200, 400, 200, 400]

    def test_empty_batch(self):
        from mmlspark_tpu.serving import ImageServingHandler

        out = ImageServingHandler(_mini_bundle()).parse(_image_request_frame([]))
        assert len(out) == 0
