"""graftcheck's own tests: every rule fires on a seeded fixture violation,
suppressions work, and the full pass over the repo is clean (the tier-1
gate the ROADMAP's "refactor freely" bet rides on).

Fixture modules under tests/resources/lint_fixtures/ are parsed, never
imported, and carry `# expect[rule]` / `# expect-suppressed[rule]` markers
on their violating lines; the tests below diff analyzer output against the
markers so fixture and assertion can't drift apart. The fixture directory
is excluded from the package-wide pass via [tool.graftcheck] exclude."""

import os
import re
import shutil
import sys
import types

from mmlspark_tpu.analysis.base import (
    RULES,
    Finding,
    apply_suppressions,
    parse_suppressions,
)
from mmlspark_tpu.analysis.config import load_config
from mmlspark_tpu.analysis.hygiene import check_broad_except
from mmlspark_tpu.analysis.jit_safety import check_jit_safety
from mmlspark_tpu.analysis.params_contract import (
    check_docs_drift,
    check_params_contract,
    check_registry_exports,
)
from mmlspark_tpu.analysis.runner import run_all
from mmlspark_tpu.analysis.schema_flow import check_schema_flow
from mmlspark_tpu.core.params import Param, TypeConverters
from mmlspark_tpu.core.pipeline import Transformer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "resources", "lint_fixtures")

_EXPECT_RE = re.compile(r"#\s*expect(-suppressed)?\[([a-z\-]+)\]")


def _expectations(fixture):
    """((line, rule) expected to survive, (line, rule) expected suppressed)."""
    expected, suppressed = set(), set()
    with open(os.path.join(FIXTURES, fixture)) as f:
        for i, line in enumerate(f, start=1):
            m = _EXPECT_RE.search(line)
            if m:
                (suppressed if m.group(1) else expected).add((i, m.group(2)))
    assert expected, f"fixture {fixture} lost its expect markers"
    return expected, suppressed


def _assert_matches_markers(fixture, findings):
    """Raw findings == all markers; post-suppression == surviving markers."""
    expected, suppressed = _expectations(fixture)
    got = {(f.line, f.rule) for f in findings if f.path.endswith(fixture)}
    assert got == expected | suppressed, (
        f"{fixture}: analyzer found {sorted(got)}, "
        f"markers say {sorted(expected | suppressed)}"
    )
    with open(os.path.join(FIXTURES, fixture)) as f:
        src = f.read()
    kept = apply_suppressions(
        [f for f in findings if f.path.endswith(fixture)],
        {f.path: src for f in findings if f.path.endswith(fixture)},
    )
    assert {(f.line, f.rule) for f in kept} == expected, (
        f"{fixture}: suppression did not drop exactly the marked lines"
    )


# -- jit-safety ---------------------------------------------------------------


def test_jit_rules_fire_and_suppress():
    findings = check_jit_safety(FIXTURES, "lint_fixtures", repo_root=FIXTURES)
    _assert_matches_markers("jit_bad.py", findings)


def test_jit_rules_cover_every_family_member():
    findings = check_jit_safety(FIXTURES, "lint_fixtures", repo_root=FIXTURES)
    fired = {f.rule for f in findings}
    assert {
        "jit-host-item", "jit-host-cast", "jit-numpy-call",
        "jit-traced-branch", "jit-print",
    } <= fired


def test_jit_pass_respects_excludes(tmp_path):
    """Excluded files contribute nothing — not even a parse. A syntax error
    in an excluded file must not abort the pass (runner feeds the config's
    path excludes through to discovery)."""
    pkg = tmp_path / "pkg"
    os.makedirs(pkg)
    (pkg / "good.py").write_text(
        "import jax\n\n@jax.jit\ndef f(x):\n    print(x)\n    return x\n"
    )
    (pkg / "broken.py").write_text("def broken(:\n")
    import pytest

    with pytest.raises(SyntaxError):
        check_jit_safety(str(pkg), "pkg", repo_root=str(tmp_path))
    findings = check_jit_safety(
        str(pkg), "pkg", repo_root=str(tmp_path),
        excluded=lambda rel: rel.endswith("broken.py"),
    )
    assert [(f.rule, f.line) for f in findings] == [("jit-print", 5)]


# -- hygiene ------------------------------------------------------------------


def test_broad_except_fires_and_suppresses():
    path = os.path.join(FIXTURES, "hygiene_bad.py")
    findings = check_broad_except([path], repo_root=FIXTURES)
    _assert_matches_markers("hygiene_bad.py", findings)


# -- hot path -----------------------------------------------------------------


def test_host_sync_in_hot_path_fires_and_suppresses():
    from mmlspark_tpu.analysis.hot_path import check_hot_path

    path = os.path.join(FIXTURES, "hot_path_bad.py")
    findings = check_hot_path([path], repo_root=FIXTURES)
    _assert_matches_markers("hot_path_bad.py", findings)


def test_host_sync_rule_ignores_non_transform_functions():
    from mmlspark_tpu.analysis.hot_path import check_hot_path

    path = os.path.join(FIXTURES, "hot_path_bad.py")
    findings = check_hot_path([path], repo_root=FIXTURES)
    # the fit() sync in the fixture must NOT be flagged
    with open(path) as f:
        fit_line = next(
            i for i, line in enumerate(f, start=1) if "def fit" in line
        )
    assert all(f.line < fit_line for f in findings)


# -- kernel fallback ----------------------------------------------------------


def test_kernel_without_fallback_fires_and_suppresses():
    from mmlspark_tpu.analysis.kernel_fallback import check_kernel_fallback

    path = os.path.join(FIXTURES, "kernel_bad.py")
    findings = check_kernel_fallback([path], repo_root=FIXTURES)
    _assert_matches_markers("kernel_bad.py", findings)


def test_kernel_rule_accepts_every_fallback_shape():
    """The three clean variants in the fixture (interpret kwarg, interpret
    parameter, *_impl dispatch beside einsum) must all pass — they are the
    exact shapes the real kernels in gbdt/compute.py and dnn/quant.py use."""
    from mmlspark_tpu.analysis.kernel_fallback import check_kernel_fallback

    path = os.path.join(FIXTURES, "kernel_bad.py")
    findings = check_kernel_fallback([path], repo_root=FIXTURES)
    with open(path) as f:
        src = f.read()
    for clean_fn in ("good_interpret_kwarg", "good_interpret_param",
                     "good_impl_dispatch"):
        assert clean_fn in src  # fixture lost a clean variant
    flagged_lines = {f.line for f in findings}
    bad_lines = {
        i for i, line in enumerate(src.splitlines(), start=1)
        if "expect[kernel-without-fallback]" in line
        or "expect-suppressed[kernel-without-fallback]" in line
    }
    assert flagged_lines == bad_lines, findings


def test_kernel_rule_package_scan_clean():
    """Every real pallas_call in the package keeps its fallback arm — the
    scan over the kernel tier's actual modules finds nothing."""
    from mmlspark_tpu.analysis.kernel_fallback import check_kernel_fallback

    paths = [
        os.path.join(REPO, "mmlspark_tpu", "gbdt", "compute.py"),
        os.path.join(REPO, "mmlspark_tpu", "dnn", "quant.py"),
    ]
    assert check_kernel_fallback(paths, repo_root=REPO) == []


# -- metric docs --------------------------------------------------------------


def test_metric_docs_fires_and_suppresses():
    from mmlspark_tpu.analysis.metric_docs import check_metric_docs

    path = os.path.join(FIXTURES, "metric_docs_bad.py")
    findings = check_metric_docs([path], repo_root=FIXTURES)
    _assert_matches_markers("metric_docs_bad.py", findings)


def test_metric_docs_table_extraction():
    """Doc-table parsing honors the table idioms the real doc uses: a
    trailing {label} group is labels, an interior brace group expands as
    alternation, and prose mentions outside table rows do not count."""
    from mmlspark_tpu.analysis.metric_docs import documented_families

    names = documented_families(
        "mentions `prose_only_total` in prose\n"
        "| metric | source |\n"
        "|---|---|\n"
        "| `plain_total` | x |\n"
        "| `labeled_ms{engine,code}` | x |\n"
        "| `alt_{a,b}_{c,d}_total` | x |\n"
    )
    assert names == {
        "plain_total", "labeled_ms",
        "alt_a_c_total", "alt_a_d_total",
        "alt_b_c_total", "alt_b_d_total",
    }


def test_metric_docs_missing_doc_flags_everything(tmp_path):
    """With no docs/observability.md at the root, every registration is
    undocumented — the rule must not silently pass on a doc-less tree."""
    from mmlspark_tpu.analysis.metric_docs import check_metric_docs

    mod = tmp_path / "m.py"
    mod.write_text('reg.counter("lonely_total", "h")\n')
    findings = check_metric_docs([str(mod)], repo_root=str(tmp_path))
    assert [(f.rule, f.line) for f in findings] == [
        ("undocumented-metric-family", 1)
    ]


def test_metric_docs_package_scan_clean():
    """Every family the package registers appears in docs/observability.md's
    metric tables — the contract this rule exists to pin."""
    findings = run_all(REPO, select=["undocumented-metric-family"])
    assert findings == [], [str(f) for f in findings]


# -- batch loop ---------------------------------------------------------------


def test_full_materialize_in_stream_path_fires_and_suppresses():
    from mmlspark_tpu.analysis.full_materialize import check_full_materialize

    path = os.path.join(FIXTURES, "stream_bad.py")
    findings = check_full_materialize([path], repo_root=FIXTURES)
    _assert_matches_markers("stream_bad.py", findings)


def test_full_materialize_allows_bounded_chunk_conversion():
    from mmlspark_tpu.analysis.full_materialize import check_full_materialize

    path = os.path.join(FIXTURES, "stream_bad.py")
    findings = check_full_materialize([path], repo_root=FIXTURES)
    # per-batch to_numpy on iter_batches RecordBatches (the streaming
    # idiom, clean_bounded_chunks) must never fire
    with open(path) as f:
        clean_line = next(
            i for i, line in enumerate(f, start=1)
            if "def clean_bounded_chunks" in line
        )
    assert all(f.line < clean_line for f in findings), findings


def test_host_roundtrip_in_batch_loop_fires_and_suppresses():
    from mmlspark_tpu.analysis.batch_loop import check_batch_loop

    path = os.path.join(FIXTURES, "batch_loop_bad.py")
    findings = check_batch_loop([path], repo_root=FIXTURES)
    _assert_matches_markers("batch_loop_bad.py", findings)


def test_batch_loop_rule_allows_converters_and_batched_calls():
    from mmlspark_tpu.analysis.batch_loop import check_batch_loop

    path = os.path.join(FIXTURES, "batch_loop_bad.py")
    findings = check_batch_loop([path], repo_root=FIXTURES)
    # nothing in the clean_paths section fires except the suppressed line:
    # np.asarray/np.stack per row (the staging-for-one-batched-call idiom),
    # batched ops on the whole stack, and non-column loops are all clean
    with open(path) as f:
        clean_line = next(
            i for i, line in enumerate(f, start=1) if "def clean_paths" in line
        )
    suppressed = {
        line for line, rule in _expectations("batch_loop_bad.py")[1]
    }
    assert all(
        f.line < clean_line or f.line in suppressed for f in findings
    ), findings


def test_batch_loop_rule_scoped_to_image_tiers(tmp_path):
    """run_all only feeds images/featurize/stages modules to the rule: the
    same per-row pattern in, say, serving/ is out of scope."""
    pkg = tmp_path / "mmlspark_tpu"
    bad_src = (
        "import numpy as np\nfrom mmlspark_tpu.images import ops\n\n"
        "def transform(df):\n"
        "    values = df['image']\n"
        "    return [ops.resize(v, 4, 4) for v in values]\n"
    )
    for sub in ("images", "serving"):
        d = pkg / sub
        d.mkdir(parents=True)
        (d / "__init__.py").write_text("")
        (d / "mod.py").write_text(bad_src)
    (pkg / "__init__.py").write_text("")
    findings = run_all(
        root=str(tmp_path), select=["host-roundtrip-in-batch-loop"]
    )
    paths = {f.path for f in findings}
    assert os.path.join("mmlspark_tpu", "images", "mod.py") in paths
    assert not any("serving" in p for p in paths), paths


# -- lock scope ---------------------------------------------------------------


def test_blocking_host_work_under_lock_fires_and_suppresses():
    from mmlspark_tpu.analysis.lock_scope import check_lock_scope

    path = os.path.join(FIXTURES, "lock_bad.py")
    findings = check_lock_scope([path], repo_root=FIXTURES)
    _assert_matches_markers("lock_bad.py", findings)


def test_lock_scope_rule_honors_configured_lock_names():
    """The `_stats_lock` block in the fixture is clean by default; naming it
    in lock_names turns its json.dumps into a finding."""
    from mmlspark_tpu.analysis.lock_scope import check_lock_scope

    path = os.path.join(FIXTURES, "lock_bad.py")
    findings = check_lock_scope(
        [path], repo_root=FIXTURES, lock_names=["_stats_lock"]
    )
    assert len(findings) == 1
    with open(path) as f:
        stats_line = next(
            i for i, line in enumerate(f, start=1)
            if "not a configured model lock" in line
        )
    assert findings[0].line == stats_line


def test_lock_scope_config_key_loads(tmp_path):
    (tmp_path / "pyproject.toml").write_text(
        '[tool.graftcheck]\nlock_names = ["_engine_lock"]\n'
    )
    cfg = load_config(str(tmp_path))
    assert cfg.lock_names == ["_engine_lock"]
    assert load_config(REPO).lock_names == ["_model_lock"]  # default


# -- monotonic time -----------------------------------------------------------


def test_non_monotonic_duration_fires_and_suppresses():
    from mmlspark_tpu.analysis.monotonic_time import check_monotonic_time

    path = os.path.join(FIXTURES, "nonmono_bad.py")
    findings = check_monotonic_time([path], repo_root=FIXTURES)
    _assert_matches_markers("nonmono_bad.py", findings)


def test_non_monotonic_rule_allows_bare_timestamps_and_monotonic():
    """A bare time.time() with no duration math, and any time.monotonic/
    perf_counter arithmetic, must not be flagged."""
    from mmlspark_tpu.analysis.monotonic_time import check_monotonic_time

    path = os.path.join(FIXTURES, "nonmono_bad.py")
    findings = check_monotonic_time([path], repo_root=FIXTURES)
    with open(path) as f:
        clean_lines = {
            i for i, line in enumerate(f, start=1) if "clean" in line
        }
    assert not {f.line for f in findings} & clean_lines


def test_non_monotonic_rule_scopes_taint_per_function(tmp_path):
    """A wall read in an enclosing scope must not taint a nested function's
    own (correct) perf_counter math."""
    from mmlspark_tpu.analysis.monotonic_time import check_monotonic_time

    p = tmp_path / "scoped.py"
    p.write_text(
        "import time\n\n"
        "def outer():\n"
        "    t0 = time.time()\n"
        "    def inner():\n"
        "        s = time.perf_counter()\n"
        "        return time.perf_counter() - s\n"
        "    return t0, inner\n"
    )
    assert check_monotonic_time([str(p)], repo_root=str(tmp_path)) == []


# -- network timeouts ---------------------------------------------------------


def test_network_call_no_timeout_fires_and_suppresses():
    from mmlspark_tpu.analysis.net_timeout import check_net_timeout

    path = os.path.join(FIXTURES, "net_bad.py")
    findings = check_net_timeout([path], repo_root=FIXTURES)
    _assert_matches_markers("net_bad.py", findings)


def test_network_rule_allows_timeouts_and_unrelated_calls():
    """Keyword and positional timeouts, **kwargs splats, and methods that
    merely share the create_connection name must not be flagged."""
    from mmlspark_tpu.analysis.net_timeout import check_net_timeout

    path = os.path.join(FIXTURES, "net_bad.py")
    findings = check_net_timeout([path], repo_root=FIXTURES)
    with open(path) as f:
        clean_lines = {
            i for i, line in enumerate(f, start=1) if "clean" in line
        }
    assert not {f.line for f in findings} & clean_lines


# -- cross-process tracing ----------------------------------------------------


def test_untraced_cross_process_call_fires_and_suppresses():
    from mmlspark_tpu.analysis.cross_process import check_cross_process

    path = os.path.join(FIXTURES, "trace_bad.py")
    findings = check_cross_process([path], repo_root=FIXTURES)
    _assert_matches_markers("trace_bad.py", findings)


def test_cross_process_rule_allows_injected_headers():
    """Every visible injection shape — direct inject call, assignment from
    one, mutation by one, explicit traceparent stores, literal dicts and
    **kwargs splats — must pass, as must non-HTTP .request lookalikes."""
    from mmlspark_tpu.analysis.cross_process import check_cross_process

    path = os.path.join(FIXTURES, "trace_bad.py")
    findings = check_cross_process([path], repo_root=FIXTURES)
    with open(path) as f:
        clean_lines = {
            i for i, line in enumerate(f, start=1) if "clean" in line
        }
    assert not {f.line for f in findings} & clean_lines


def test_cross_process_rule_scoped_to_serving(tmp_path):
    """The runner only feeds serving/ files to the rule: an untraced
    .request elsewhere in the package (a downloader, a test client) is not
    a gateway hop and must not fail the package scan."""
    pkg = tmp_path / "mmlspark_tpu"
    (pkg / "serving").mkdir(parents=True)
    (pkg / "downloader").mkdir()
    bad = "def f(conn, body):\n    conn.request('POST', '/x', body)\n"
    (pkg / "serving" / "gw.py").write_text(bad)
    (pkg / "downloader" / "dl.py").write_text(bad)
    (pkg / "__init__.py").write_text("")
    (pkg / "serving" / "__init__.py").write_text("")
    (pkg / "downloader" / "__init__.py").write_text("")
    findings = [
        f for f in run_all(
            str(tmp_path), select=["untraced-cross-process-call"]
        )
        if f.rule == "untraced-cross-process-call"
    ]
    assert [f.path for f in findings] == [
        os.path.join("mmlspark_tpu", "serving", "gw.py")
    ]


def test_gateway_forward_path_is_traced():
    """The live package scan proves the tentpole wiring: every
    cross-process send in mmlspark_tpu/serving/ carries visible
    traceparent injection (distributed.py's forward + rebuild paths)."""
    from mmlspark_tpu.analysis.cross_process import check_cross_process

    serving = os.path.join(REPO, "mmlspark_tpu", "serving")
    paths = [
        os.path.join(serving, f)
        for f in sorted(os.listdir(serving)) if f.endswith(".py")
    ]
    assert check_cross_process(paths, repo_root=REPO) == []


# -- atomic artifact writes ---------------------------------------------------


def test_non_atomic_artifact_write_fires_and_suppresses():
    from mmlspark_tpu.analysis.atomic_write import check_atomic_write

    path = os.path.join(FIXTURES, "atomic_bad.py")
    findings = check_atomic_write([path], repo_root=FIXTURES)
    _assert_matches_markers("atomic_bad.py", findings)


def test_atomic_write_rule_allows_staged_writes_and_reads():
    """tmp-named staging paths, functions that publish with os.replace,
    tempfile-staged siblings, and read-mode opens must not be flagged."""
    from mmlspark_tpu.analysis.atomic_write import check_atomic_write

    path = os.path.join(FIXTURES, "atomic_bad.py")
    findings = check_atomic_write([path], repo_root=FIXTURES)
    with open(path) as f:
        clean_lines = {
            i for i, line in enumerate(f, start=1) if "clean" in line
        }
    assert not {f.line for f in findings} & clean_lines


def test_atomic_write_package_scan_clean():
    """ISSUE 8 satellite: the persistence tier (io/, core/serialize,
    dnn/network, gbdt/booster) routes every artifact write through the
    atomic helpers — the scoped scan must stay clean."""
    findings = run_all(REPO, select=["non-atomic-artifact-write"])
    assert findings == [], [str(f) for f in findings]


def test_atomic_write_rule_scoped_to_persistence_modules(tmp_path):
    """A non-persistence module writing a file in place is out of scope for
    this rule (other rules own other tiers): the runner only hands the
    checker io/ + the named persistence modules."""
    from mmlspark_tpu.analysis.atomic_write import check_atomic_write

    mod = tmp_path / "elsewhere.py"
    mod.write_text(
        "def dump(path, s):\n"
        "    with open(path, 'w') as f:\n"
        "        f.write(s)\n"
    )
    # the checker itself flags it...
    assert check_atomic_write([str(mod)], repo_root=str(tmp_path))
    # ...but the package scan above is clean even though e.g.
    # obs/tracing.py and bench-adjacent modules write files in place,
    # proving the runner's persistence-tier scoping is in effect.


# -- schema flow --------------------------------------------------------------


def test_schema_flow_fires_and_suppresses():
    path = os.path.join(FIXTURES, "flow_bad.py")
    findings = check_schema_flow([path], repo_root=FIXTURES)
    _assert_matches_markers("flow_bad.py", findings)


# -- Params contracts (fixture classes live here: reflection needs objects) --


class _BadParamStage(Transformer):
    """Fixture: one seeded violation per Params-contract rule."""

    undocumented = Param("undocumented", "", TypeConverters.to_int)
    unconverted = Param("unconverted", "Simple param without a converter")
    bad_default = Param(
        "bad_default", "Default violates its converter", TypeConverters.to_string
    )

    def __init__(self):
        super().__init__()
        self._set_default("bad_default", 7)


class _NoRoundTrip(Transformer):
    """Fixture: a set simple param JSON can't carry fails the save."""

    blob = Param("blob", "Non-serializable payload", TypeConverters.to_dict)

    def __init__(self):
        super().__init__()
        self.set("blob", {"f": lambda: None})  # callables don't JSON


def test_params_contract_rules_fire():
    findings = check_params_contract(
        classes={"fixtures._BadParamStage": _BadParamStage}, repo_root=REPO
    )
    rules = sorted(f.rule for f in findings)
    assert rules == ["param-converter", "param-default", "param-doc"], [
        str(f) for f in findings
    ]


def test_stage_roundtrip_rule_fires():
    findings = check_params_contract(
        classes={"fixtures._NoRoundTrip": _NoRoundTrip}, repo_root=REPO
    )
    assert [f.rule for f in findings] == ["stage-roundtrip"], [
        str(f) for f in findings
    ]


def test_params_contract_clean_control():
    from mmlspark_tpu.stages.basic import DropColumns

    assert check_params_contract(
        classes={"mmlspark_tpu.stages.basic.DropColumns": DropColumns},
        repo_root=REPO,
    ) == []


# -- registry integrity (satellite: registry.py:45 enforced) ------------------


class _OrphanTransformer(Transformer):
    """Fixture: a public export the registry does not contain."""


def test_registry_export_rule_fires_on_unregistered_class():
    fake = types.ModuleType("fake_subpkg")
    fake.__all__ = ["OrphanTransformer"]
    fake.OrphanTransformer = _OrphanTransformer
    findings = check_registry_exports(modules=[fake], repo_root=REPO)
    assert [f.rule for f in findings] == ["registry-export"]
    assert "OrphanTransformer" in findings[0].message


def test_every_public_stage_export_is_registered():
    """The 'import failure is a bug' comment in core/registry.py, enforced:
    each public Transformer/Estimator exported from mmlspark_tpu/*/__init__
    is present in the registry."""
    assert check_registry_exports(repo_root=REPO) == []


# -- docs drift ---------------------------------------------------------------


def test_docs_drift_fires_on_missing_page(tmp_path):
    shutil.copytree(
        os.path.join(REPO, "docs", "api"), tmp_path / "docs" / "api"
    )
    os.makedirs(tmp_path / "tools")
    shutil.copy(
        os.path.join(REPO, "tools", "codegen.py"), tmp_path / "tools"
    )
    os.remove(tmp_path / "docs" / "api" / "INDEX.md")
    findings = check_docs_drift(repo_root=str(tmp_path))
    assert any(
        f.rule == "docs-drift" and "INDEX.md" in f.path for f in findings
    )


# -- config / suppression plumbing -------------------------------------------


def test_parse_suppressions_forms():
    src = (
        "a = 1  # graftcheck: ignore\n"
        "b = 2  # graftcheck: ignore[jit-print]\n"
        "c = 3  # graftcheck: ignore[jit-print, broad-except]\n"
        "d = 4\n"
    )
    sup = parse_suppressions(src)
    assert sup[1] is None
    assert sup[2] == {"jit-print"}
    assert sup[3] == {"jit-print", "broad-except"}
    assert 4 not in sup


def test_config_loads_pyproject_table():
    cfg = load_config(REPO)
    assert "tests/resources/lint_fixtures" in cfg.exclude
    assert cfg.path_excluded("tests/resources/lint_fixtures/jit_bad.py")
    assert not cfg.path_excluded("tests/test_core.py")


def test_mini_toml_fallback_parses_our_table():
    from mmlspark_tpu.analysis.config import _mini_toml

    data = _mini_toml(
        '[tool.graftcheck]\ndisable = ["docs-drift"]\n'
        'exclude = [\n  "a/b",\n  "c/d",\n]\n'
    )
    assert data["tool"]["graftcheck"]["disable"] == ["docs-drift"]
    assert data["tool"]["graftcheck"]["exclude"] == ["a/b", "c/d"]


def test_unknown_rule_id_rejected():
    import pytest

    with pytest.raises(ValueError, match="unknown graftcheck rule"):
        run_all(root=REPO, select=["not-a-rule"])


def test_cli_unknown_rule_is_usage_error(capsys):
    tools_dir = os.path.join(REPO, "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import lint

    assert lint.main(["--select", "not-a-rule"]) == 2
    assert "error:" in capsys.readouterr().err


def test_select_overrides_config_disable(tmp_path):
    """A user driving one rule explicitly must actually run it, even when
    the config disables it for the default pass."""
    (tmp_path / "pyproject.toml").write_text(
        '[tool.graftcheck]\ndisable = ["broad-except"]\n'
    )
    pkg = tmp_path / "pkg"
    os.makedirs(pkg)
    (pkg / "__init__.py").write_text("")
    (pkg / "bad.py").write_text(
        "def f(fn):\n    try:\n        return fn()\n"
        "    except Exception:\n        return None\n"
    )
    default = run_all(root=str(tmp_path), package_name="pkg")
    assert [f.rule for f in default] == []
    selected = run_all(
        root=str(tmp_path), select=["broad-except"], package_name="pkg"
    )
    assert [f.rule for f in selected] == ["broad-except"]


def test_cli_list_rules(capsys):
    tools_dir = os.path.join(REPO, "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import lint

    assert lint.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in RULES:
        assert rule in out


def test_finding_str_is_clickable():
    f = Finding("jit-print", "mmlspark_tpu/x.py", 12, "boom")
    assert str(f) == "mmlspark_tpu/x.py:12: jit-print: boom"


# -- THE tier-1 gate ----------------------------------------------------------


def test_package_lint_clean():
    """`python tools/lint.py mmlspark_tpu` and this test share run_all():
    the entire repo must pass every graftcheck rule."""
    findings = run_all(root=REPO)
    assert not findings, "graftcheck findings:\n" + "\n".join(
        str(f) for f in findings
    )


# -- unstructured logging -----------------------------------------------------


def test_unstructured_log_fires_and_suppresses():
    from mmlspark_tpu.analysis.unstructured_log import check_unstructured_log

    path = os.path.join(FIXTURES, "log_bad.py")
    findings = check_unstructured_log([path], repo_root=FIXTURES)
    _assert_matches_markers("log_bad.py", findings)


def test_unstructured_log_allows_structured_and_lookalikes():
    """obs.logging.get_logger imports/calls, methods merely named print,
    and substring lookalikes (fingerprint) must not be flagged."""
    from mmlspark_tpu.analysis.unstructured_log import check_unstructured_log

    path = os.path.join(FIXTURES, "log_bad.py")
    findings = check_unstructured_log([path], repo_root=FIXTURES)
    with open(path) as f:
        clean_lines = {
            i for i, line in enumerate(f, start=1) if "clean" in line
        }
    assert not {f.line for f in findings} & clean_lines


def test_unstructured_log_exempts_obs_logging_module(tmp_path):
    """obs/logging.py is the one module allowed to own the stdlib
    machinery — the rule must skip it wherever the repo root lives."""
    from mmlspark_tpu.analysis.unstructured_log import check_unstructured_log

    pkg = tmp_path / "obs"
    pkg.mkdir()
    allowed = pkg / "logging.py"
    allowed.write_text(
        "import logging\n\n"
        "def stdlib_logger(name):\n"
        "    return logging.getLogger(name)\n"
    )
    other = tmp_path / "other.py"
    other.write_text(
        "import logging\n\n"
        "def bad():\n"
        "    return logging.getLogger('x')\n"
    )
    findings = check_unstructured_log(
        [str(allowed), str(other)], repo_root=str(tmp_path)
    )
    assert {f.path for f in findings} == {"other.py"}


# -- untracked device uploads --------------------------------------------------


def test_untracked_upload_fires_and_suppresses():
    from mmlspark_tpu.analysis.untracked_upload import check_untracked_upload

    path = os.path.join(FIXTURES, "upload_bad.py")
    findings = check_untracked_upload([path], repo_root=FIXTURES)
    _assert_matches_markers("upload_bad.py", findings)


def test_untracked_upload_allows_counted_scopes():
    """upload_host_chunk routing, record_h2d-counted scopes, ledgered
    scopes, asarray without device=, and bare aliases must stay silent."""
    from mmlspark_tpu.analysis.untracked_upload import check_untracked_upload

    path = os.path.join(FIXTURES, "upload_bad.py")
    findings = check_untracked_upload([path], repo_root=FIXTURES)
    with open(path) as f:
        clean_line = next(
            i for i, line in enumerate(f, start=1)
            if "def clean_via_upload_host_chunk" in line
        )
    assert findings and all(f.line < clean_line for f in findings), findings


def test_untracked_upload_scoped_to_dataplane_tier(tmp_path):
    """run_all only feeds the dataplane-tier modules to the rule: the same
    bare device_put in, say, serving/ is another tier's business."""
    pkg = tmp_path / "mmlspark_tpu"
    bad_src = (
        "import jax\n\n"
        "def stage(host):\n"
        "    return jax.device_put(host)\n"
    )
    for sub, name in (("core", "dataframe.py"), ("serving", "mod.py")):
        d = pkg / sub
        d.mkdir(parents=True)
        (d / "__init__.py").write_text("")
        (d / name).write_text(bad_src)
    (pkg / "__init__.py").write_text("")
    findings = run_all(
        root=str(tmp_path), select=["untracked-device-upload"]
    )
    paths = {f.path for f in findings}
    assert os.path.join("mmlspark_tpu", "core", "dataframe.py") in paths
    assert not any("serving" in p for p in paths), paths


def test_untracked_upload_package_scan_clean():
    """ISSUE 16 satellite: every dataplane-tier upload is counted — the
    column/prefetch/mesh record_h2d sites, the weight uploads' ledger
    records, and the fused GBDT engine's counted shard/mask uploads."""
    findings = run_all(root=REPO, select=["untracked-device-upload"])
    assert findings == [], [str(f) for f in findings]


# -- per-step host sync in train loop ------------------------------------------


def test_train_loop_sync_fires_and_suppresses():
    from mmlspark_tpu.analysis.train_loop import check_train_loop

    path = os.path.join(FIXTURES, "train_sync_bad.py")
    findings = check_train_loop([path], repo_root=FIXTURES)
    _assert_matches_markers("train_sync_bad.py", findings)


def test_train_loop_rule_ignores_epoch_end_fetch_and_other_functions():
    """The accumulate-then-fetch idiom (device_get after the loop) and
    per-step syncs in functions outside fit*/train* must stay silent."""
    from mmlspark_tpu.analysis.train_loop import check_train_loop

    path = os.path.join(FIXTURES, "train_sync_bad.py")
    findings = check_train_loop([path], repo_root=FIXTURES)
    with open(path) as f:
        fit_end = next(
            i for i, line in enumerate(f, start=1)
            if "def _train" in line
        )
    assert findings and all(f.line < fit_end for f in findings), findings


def test_train_loop_rule_scoped_to_training_tiers(tmp_path):
    """run_all only feeds models/ and automl/ to the rule: the same
    per-step float() in, say, serving/ is another tier's business."""
    pkg = tmp_path / "mmlspark_tpu"
    bad_src = (
        "import jax\n\n"
        "def fit(batches):\n"
        "    step = jax.jit(lambda b: b)\n"
        "    for b in batches:\n"
        "        out = step(b)\n"
        "        val = float(out)\n"
        "    return val\n"
    )
    for sub in ("models", "automl", "serving"):
        d = pkg / sub
        d.mkdir(parents=True)
        (d / "__init__.py").write_text("")
        (d / "mod.py").write_text(bad_src)
    (pkg / "__init__.py").write_text("")
    findings = run_all(
        root=str(tmp_path), select=["per-step-host-sync-in-train-loop"]
    )
    paths = {f.path for f in findings}
    assert os.path.join("mmlspark_tpu", "models", "mod.py") in paths
    assert os.path.join("mmlspark_tpu", "automl", "mod.py") in paths
    assert not any("serving" in p for p in paths), paths


def test_train_loop_package_scan_clean():
    """PR 18 satellite: the training tiers carry no per-step host sync —
    the learner's epoch loop appends device scalars and device_gets them
    once per epoch."""
    findings = run_all(root=REPO, select=["per-step-host-sync-in-train-loop"])
    assert findings == [], [str(f) for f in findings]


# -- hardcoded device index ----------------------------------------------------


def test_device_index_fires_and_suppresses():
    from mmlspark_tpu.analysis.device_index import check_device_index

    path = os.path.join(FIXTURES, "device_index_bad.py")
    findings = check_device_index([path], repo_root=FIXTURES)
    _assert_matches_markers("device_index_bad.py", findings)


def test_device_index_honors_guards_and_slices():
    """Single-device-guarded branches and prefix slices (device-SET
    selection for mesh construction) must stay silent."""
    from mmlspark_tpu.analysis.device_index import check_device_index

    path = os.path.join(FIXTURES, "device_index_bad.py")
    findings = check_device_index([path], repo_root=FIXTURES)
    with open(path) as f:
        src = f.read().splitlines()
    guarded = {
        i for i, line in enumerate(src, start=1)
        if "jax.devices()[0]" in line and "expect" not in line
    }
    assert guarded, "fixture lost its guarded/clean uses"
    assert not {f.line for f in findings} & guarded


def test_device_index_package_scan_clean_via_runner():
    """The live package passes the rule through run_all — the trainer's
    shard->device ownership and env.py's kind probe stay index-free (the
    PR 15 mesh-sharding contract)."""
    findings = run_all(root=REPO, select=["hardcoded-device-index"])
    assert not findings, "\n".join(str(f) for f in findings)
