"""Container serving entrypoint: load a saved stage and serve it.

Usage (inside the image, or anywhere the package is installed):
    python serve_entrypoint.py --model /models/my_model \
        --host 0.0.0.0 --port 8000 --api score \
        --input-schema '{"features": "vector"}' --reply-col prediction

The model directory is anything `mmlspark_tpu.core.serialize.load_stage`
reads back — a fitted pipeline, a LightGBM model, a TPUModel, ... The HTTP
contract is the serving tier's (docs/serving.md): POST JSON to /<api>,
reply is the reply column serialized back.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", required=True, help="saved stage directory")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--api", default="score")
    ap.add_argument("--reply-col", default="prediction")
    ap.add_argument("--mode", default="micro_batch",
                    choices=["continuous", "micro_batch"])
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument(
        "--input-schema", default=None,
        help='JSON {"col": "double"|"vector"|"string"} request schema',
    )
    args = ap.parse_args(argv)

    from mmlspark_tpu.core.dataframe import DataType
    from mmlspark_tpu.core.serialize import load_stage
    from mmlspark_tpu.serving import DistributedServingServer, serve_pipeline

    # Block shutdown signals BEFORE any server threads spawn: masks are
    # per-thread and inherited at creation, and a process-directed SIGTERM
    # delivered to an unblocked worker thread would kill the process before
    # stop() can drain.
    signal.pthread_sigmask(signal.SIG_BLOCK, {signal.SIGINT, signal.SIGTERM})

    schema = None
    if args.input_schema:
        schema = {
            k: DataType(v) for k, v in json.loads(args.input_schema).items()
        }

    if args.workers > 1:
        from mmlspark_tpu.serving import make_reply, parse_request

        def handler_factory():
            # one model replica PER WORKER: stages may hold per-instance
            # state (caches, clients) and workers only serialize through
            # their own model lock (serving/distributed.py contract)
            replica = load_stage(args.model)

            def handler(df):
                parsed = parse_request(df, schema)
                return make_reply(replica.transform(parsed), args.reply_col)
            return handler

        server = DistributedServingServer(
            handler_factory, n_workers=args.workers, host=args.host,
            port=args.port, api_name=args.api, mode=args.mode,
        ).start()
    else:
        server = serve_pipeline(
            load_stage(args.model), input_schema=schema, host=args.host,
            port=args.port, api_name=args.api, reply_col=args.reply_col,
            mode=args.mode,
        ).start()

    print(f"serving {args.model} at {server.url}", flush=True)
    signal.sigwait({signal.SIGINT, signal.SIGTERM})
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
