"""Micro-profile of grow_tree_fused loop-body components."""

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, n=5, warmup=1):
    for _ in range(warmup):
        jax.block_until_ready(fn())
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


F, B, L, N = 14, 256, 31, 39936
rng = np.random.default_rng(0)
hist = jax.device_put(rng.normal(size=(F, B, 3)).astype(np.float32))
key = jax.device_put(rng.normal(size=(F, B)).astype(np.float32))
bins = jax.device_put(rng.integers(0, B, size=(N, F)).astype(np.int32))
g = jax.device_put(rng.normal(size=N).astype(np.float32))
h = jax.device_put(np.abs(rng.normal(size=N)).astype(np.float32))
mask = jax.device_put(np.ones(N, bool))

# 1. argsort (F, B)
f_sort = jax.jit(lambda k: jnp.argsort(k, axis=1))
print(f"argsort_FB_ms: {timeit(lambda: f_sort(key))*1e3:.3f}")

# 2. two argsorts + take_along_axis + cumsums (the one_dir body)
@jax.jit
def one_dir(k, hh):
    order = jnp.argsort(k, axis=1)
    g_s = jnp.take_along_axis(hh[..., 0], order, 1)
    h_s = jnp.take_along_axis(hh[..., 1], order, 1)
    c_s = jnp.take_along_axis(hh[..., 2], order, 1)
    return jnp.cumsum(g_s, 1) + jnp.cumsum(h_s, 1) + jnp.cumsum(c_s, 1)

print(f"one_dir_ms: {timeit(lambda: one_dir(key, hist))*1e3:.3f}")

# 3. comparison-matrix prefix (argsort-free categorical scan)
@jax.jit
def cmp_prefix(k, hh):
    idx = jnp.arange(B)
    le = (k[:, None, :] < k[:, :, None]) | (
        (k[:, None, :] == k[:, :, None]) & (idx[None, None, :] <= idx[None, :, None])
    )
    return jnp.einsum("fij,fjv->fiv", le.astype(jnp.float32), hh,
                      preferred_element_type=jnp.float32)

print(f"cmp_prefix_ms: {timeit(lambda: cmp_prefix(key, hist))*1e3:.3f}")

# 4. full-data masked histogram (as inside loop body)
from mmlspark_tpu.gbdt.compute import _hist_masked

f_hist = jax.jit(lambda m: _hist_masked(bins, g, h, m, B))
print(f"hist_masked_ms: {timeit(lambda: f_hist(mask))*1e3:.3f}")

# 5. assign-update gather+where over n rows
@jax.jit
def route(assign, member, fcol):
    go_left = member[fcol]
    return jnp.where((assign == 3) & ~go_left, 7, assign).astype(jnp.int32)

assign = jax.device_put(np.zeros(N, np.int32))
member = jax.device_put(np.ones(B, bool))
fcol = jax.device_put(rng.integers(0, B, N).astype(np.int32))
print(f"route_ms: {timeit(lambda: route(assign, member, fcol))*1e3:.3f}")

# 6. while_loop of 30 trivial steps over the big state (state-copy overhead)
def mk_state():
    return dict(
        assign=jnp.zeros(N, jnp.int32),
        hists=jnp.zeros((L, F, B, 3), jnp.float32),
        best_member=jnp.zeros((L, B), bool),
        node_member=jnp.zeros((L, B), bool),
        step=jnp.int32(0),
    )

@jax.jit
def wl_trivial(st):
    def body(s):
        s["hists"] = s["hists"].at[0].set(s["hists"][1] + 1.0)
        s["step"] = s["step"] + 1
        return s
    return jax.lax.while_loop(lambda s: s["step"] < 30, body, st)["step"]

print(f"whileloop30_trivial_ms: {timeit(lambda: wl_trivial(mk_state()))*1e3:.3f}")

# 7. while_loop of 30 steps doing hist + 2x(2x one_dir) (approx real body)
@jax.jit
def wl_real(st):
    def body(s):
        m = mask & (s["assign"] == 0)
        hh = _hist_masked(bins, g, h, m, B)
        acc = 0.0
        for _ in range(2):      # two children
            for sgn in (1.0, -1.0):  # two directions
                acc = acc + one_dir_body(sgn * key, hh)
        s["hists"] = s["hists"].at[0].set(hh + acc * 0.0)
        s["step"] = s["step"] + 1
        return s
    return jax.lax.while_loop(lambda s: s["step"] < 30, body, st)["step"]

def one_dir_body(k, hh):
    order = jnp.argsort(k, axis=1)
    g_s = jnp.take_along_axis(hh[..., 0], order, 1)
    return jnp.cumsum(g_s, 1)[:, :, None] * jnp.ones((1, 1, 3))

print(f"whileloop30_hist+4argsort_ms: {timeit(lambda: wl_real(mk_state()))*1e3:.3f}")
