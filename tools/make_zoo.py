"""Build the committed model zoo (models_zoo/).

Trains ConvNet_patches: a small convnet on the synthetic two-patch XOR task
(class = XOR of two bright-patch indicators) — a task linear raw-pixel
models CANNOT solve, so transfer-learning tests can prove the featurizer's
penultimate activations carry non-linear information (the role the
reference's CNTK zoo models play for ImageFeaturizerSuite).

Run from the repo root:  python tools/make_zoo.py
Deterministic (fixed seeds) so the committed hash is reproducible.

Reference: downloader ModelDownloader.scala:209-267 (the zoo this seeds),
ImageFeaturizer.scala:73-79 (layerNames consumption).
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.dnn.network import Network
from mmlspark_tpu.downloader import ModelDownloader
from mmlspark_tpu.models.tpu_learner import TPULearner

H = W = 32
PATCH = 8


def make_patch_xor(n: int, seed: int = 0):
    """Images with optional bright patches at top-left / bottom-right;
    label = XOR of the two patch indicators."""
    rng = np.random.default_rng(seed)
    imgs = rng.integers(0, 60, size=(n, H, W, 3)).astype(np.uint8)
    p1 = rng.integers(0, 2, n).astype(bool)
    p2 = rng.integers(0, 2, n).astype(bool)
    imgs[p1, 4:4 + PATCH, 4:4 + PATCH] = 220
    imgs[p2, 20:20 + PATCH, 20:20 + PATCH] = 220
    labels = (p1 ^ p2).astype(np.float64)
    return imgs, labels


def patch_net() -> Network:
    spec = [
        {"kind": "conv", "name": "conv1", "filters": 8, "kernel": 5, "stride": 2},
        {"kind": "batchnorm", "name": "bn1"},
        {"kind": "relu", "name": "relu1"},
        {"kind": "conv", "name": "conv2", "filters": 16, "kernel": 3, "stride": 2},
        {"kind": "batchnorm", "name": "bn2"},
        {"kind": "relu", "name": "relu2"},
        {"kind": "global_avg_pool", "name": "pool"},
        {"kind": "flatten", "name": "flat"},
        {"kind": "dense", "name": "hidden", "units": 32},
        {"kind": "relu", "name": "relu3"},
        {"kind": "dense", "name": "z", "units": 2},
    ]
    return Network(spec, input_shape=(H, W, 3))


def main() -> None:
    imgs, labels = make_patch_xor(3000, seed=0)
    # RAW 0-255 pixel scale: ImageFeaturizer feeds unrolled uint8 pixels, so
    # the published model must own its input scale (the reference's CNTK zoo
    # models likewise embed their preprocessing)
    x = imgs.reshape(len(imgs), -1).astype(np.float32)
    df = DataFrame.from_dict({"features": x, "label": labels})

    learner = TPULearner(
        patch_net(),
        loss="softmax_cross_entropy",
        optimizer="adam",
        learning_rate=3e-3,
        epochs=12,
        batch_size=128,
        seed=0,
    )
    model = learner.fit(df)
    bundle = model.get_model()

    # quick train-accuracy report (should be ~1.0; XOR is unlearnable
    # linearly, so >0.9 proves the conv trunk learned the interaction)
    scores = model.transform(df)["scores"]
    acc = float((np.argmax(scores, axis=1) == labels).mean())
    print(f"train accuracy: {acc:.4f}")
    if acc < 0.95:
        raise SystemExit("zoo model underfit; not publishing")

    tmp = os.path.join("/tmp", "zoo_build", "ConvNet_patches")
    os.makedirs(os.path.dirname(tmp), exist_ok=True)
    bundle.save_to_dir(tmp)

    repo_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "mmlspark_tpu", "models_zoo",
    )
    schema = ModelDownloader.publish(
        tmp,
        repo_dir,
        name="ConvNet",
        dataset="patches",
        model_type="image",
        input_node=0,
        # output -> input order (ImageFeaturizer cut_output_layers indexes it):
        layer_names=["z", "relu3", "hidden", "flat", "pool"],
        extra={"accuracy": acc, "task": "patch-xor", "input_shape": [H, W, 3]},
    )
    print(f"published {schema.name}_{schema.dataset}: hash={schema.hash[:12]}... "
          f"size={schema.size}B")

    # ResNet-50 (ImageNet geometry, ~25.5M params / ~100MB of weights):
    # committed as a builder RECIPE, not a blob — the downloader rebuilds it
    # deterministically and checks the hash pinned here (the reference's
    # downloadByName("ResNet50") flow, ModelDownloader.scala:209-267).
    schema = ModelDownloader.publish_builder(
        repo_dir,
        name="ResNet50",
        dataset="ImageNet",
        builder={
            "factory": "mmlspark_tpu.dnn.zoo_builders:resnet50_random",
            "kwargs": {"num_classes": 1000, "seed": 0},
        },
        model_type="image",
        input_node=0,
        layer_names=["logits", "pool", "stage4_relu3", "stage4_relu2",
                     "stage4_relu1"],
        extra={"weights": "random-init (deterministic seed 0)",
               "input_shape": [224, 224, 3]},
    )
    print(f"published {schema.name}_{schema.dataset}: hash={schema.hash[:12]}... "
          f"size={schema.size}B (builder-backed)")


if __name__ == "__main__":
    main()
