"""Device-side GBDT kernels: histogram scatter-add, leaf assignment, tree walk.

These are the ops that touch all n rows; everything else in the grower works
on KB-sized histograms on host. All functions are jit-compiled with static
(F, B) so one program serves the whole fit, and all row-dim inputs may be
sharded over a mesh "data" axis — XLA's SPMD partitioner inserts the
cross-chip reduction for the replicated histogram output, which is exactly
the per-feature histogram allreduce the reference gets from LightGBM's
native TCP ring (SURVEY.md §2.7 item 2, TrainUtils.scala:217).
"""

from __future__ import annotations

import functools

import jax
import numpy as np


@functools.partial(jax.jit, static_argnames=("num_bins",))
def leaf_histogram(bins, grad, hess, mask, *, num_bins: int):
    """Histogram of (grad, hess, count) per (feature, bin) over masked rows.

    bins: (n, F) int32 in [0, num_bins); grad/hess: (n,) f32; mask: (n,) bool.
    -> (F, num_bins, 3) float32.
    """
    import jax.numpy as jnp

    n, f = bins.shape
    g = jnp.where(mask, grad, 0.0).astype(jnp.float32)
    h = jnp.where(mask, hess, 0.0).astype(jnp.float32)
    c = mask.astype(jnp.float32)
    # flat scatter index per (row, feature): feature*B + bin
    idx = bins + jnp.arange(f, dtype=jnp.int32)[None, :] * num_bins
    updates = jnp.stack(
        [jnp.broadcast_to(g[:, None], (n, f)),
         jnp.broadcast_to(h[:, None], (n, f)),
         jnp.broadcast_to(c[:, None], (n, f))],
        axis=-1,
    )
    flat = jnp.zeros((f * num_bins, 3), jnp.float32)
    flat = flat.at[idx.reshape(-1)].add(updates.reshape(-1, 3))
    return flat.reshape(f, num_bins, 3)


@functools.partial(jax.jit, donate_argnums=(0,))
def split_rows(assign, feature_bins, member, slot, new_slot):
    """Send rows of leaf `slot` whose feature bin is NOT in `member` to
    `new_slot` (right child). member: (B,) bool — True = go left.

    assign: (n,) int32; feature_bins: (n,) int32.
    """
    import jax.numpy as jnp

    go_left = member[feature_bins]
    return jnp.where((assign == slot) & ~go_left, new_slot, assign).astype(jnp.int32)


@functools.partial(jax.jit, donate_argnums=(0,))
def add_leaf_outputs(raw, assign, leaf_values):
    """raw += leaf_values[assign] — the training-time prediction update:
    `assign` already holds each row's final leaf, so scoring the new tree is
    one gather (no tree walk)."""
    return raw + leaf_values[assign]


def _hist_masked(bins, grad, hess, mask, num_bins: int):
    """(F, B, 3) histogram over masked rows."""
    import jax.numpy as jnp

    n, f = bins.shape
    g = jnp.where(mask, grad, 0.0).astype(jnp.float32)
    h = jnp.where(mask, hess, 0.0).astype(jnp.float32)
    c = mask.astype(jnp.float32)
    if HIST_MODE == "gather":
        vals = jnp.stack([g, h, c], axis=1)            # (n, 3)
        sv = vals[_PERM]                               # (F, n, 3) gather
        cs = jnp.cumsum(sv, axis=1)
        cs = jnp.concatenate([jnp.zeros((f, 1, 3), jnp.float32), cs], axis=1)
        bb = jnp.broadcast_to(_BOUND[:, :, None], (f, num_bins + 1, 3))
        at = jnp.take_along_axis(cs, bb, axis=1)       # (F, B+1, 3)
        return at[:, 1:] - at[:, :-1]
    if HIST_MODE == "einsum_bf16":
        vals = jnp.stack([g, h, c], axis=1).astype(jnp.bfloat16)
        oh = (bins[:, :, None] == jnp.arange(num_bins, dtype=jnp.int32)).astype(jnp.bfloat16)
        return jnp.einsum("nfb,nv->fbv", oh, vals, preferred_element_type=jnp.float32)
    idx = bins + jnp.arange(f, dtype=jnp.int32)[None, :] * num_bins
    updates = jnp.stack(
        [jnp.broadcast_to(g[:, None], (n, f)),
         jnp.broadcast_to(h[:, None], (n, f)),
         jnp.broadcast_to(c[:, None], (n, f))],
        axis=-1,
    )
    flat = jnp.zeros((f * num_bins, 3), jnp.float32)
    flat = flat.at[idx.reshape(-1)].add(updates.reshape(-1, 3))
    return flat.reshape(f, num_bins, 3)


ABL_CAT = True
ABL_ROUTE = True
ABL_ROOT = True
HIST_MODE = "scatter"   # scatter | gather | einsum_bf16
_PERM = None    # (F, n) int32 rows sorted by bin, per feature
_BOUND = None   # (F, B+1) int32 segment boundaries
ABL_HIST = True
ABL_CHILD = True


def _grow_tree_body(
    bins,            # (n, F) int32
    grad,            # (n,) f32
    hess,            # (n,) f32
    sample_mask,     # (n,) bool
    n_bins_arr,      # (F,) int32
    categorical_arr, # (F,) bool
    feature_mask,    # (F,) bool
    min_data, min_hess, l1, l2, min_gain, learning_rate,  # traced f32 scalars
    *,
    num_bins: int,
    num_leaves: int,
    depth_limit: int,
    max_cat_threshold: int,
):
    """Grow ONE leaf-wise tree entirely on device — the SURVEY §7 "fused
    kernels" design. Plain traceable function: call via grow_tree_fused for
    a standalone dispatch, or inline inside a larger program (boost_loop_fused
    scans it across the whole fit). The host grower's per-split device round
    trip (histogram fetch -> host split finder -> row routing) costs ~100 ms
    of transfer latency per split through the chip tunnel, i.e. seconds per
    tree; this program runs the whole best-first loop (num_leaves-1 fixed
    iterations with masked no-ops after convergence) in one dispatch and
    returns a single packed f32 buffer.

    Semantics match tree.find_best_split/grow_tree (LightGBM
    SerialTreeLearner): leaf-wise argmax-gain growth, sibling histogram
    subtraction, numerical splits over cumulative bins (missing bin 0
    left), sorted-categorical prefix scans from both ends, min_data /
    min_hessian / min_gain / depth constraints. Arithmetic is f32 on
    device (the host path computed gains in f64), so split choices can
    differ from the host grower in near-ties; sharded-vs-single
    determinism is unaffected because every device count runs this same
    program with a replicated histogram reduction.

    Returns (packed, leaf_values, assign):
      packed: flat f32 —
        [num_nodes, num_leaves_used,
         feat(L), thr_bin(L), is_cat(L), gain(L), internal_value(L),
         internal_count(L), left_child(L), right_child(L),
         member(L*B) row-major, leaf_value(L), leaf_count(L)]
        child entries >= 0 are node ids, negative are ~leaf_index.
      leaf_values: (L,) f32 shrunk leaf outputs (for the raw-score update)
      assign: (n,) int32 final leaf index per row
    """
    import jax.numpy as jnp

    F = bins.shape[1]
    B = num_bins
    L = num_leaves
    NEG = jnp.float32(-jnp.inf)

    def thresh(g):
        return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)

    def score(g, h):
        t = thresh(g)
        return t * t / jnp.maximum(h + l2, 1e-35)

    def leaf_out(g, h):
        return -thresh(g) / jnp.maximum(h + l2, 1e-35)

    def best_split(hist, depth_ok):
        """hist (F,B,3) -> (gain, feat, thr_bin, is_cat, member(B,),
        left(3,), right(3,)). gain=-inf when no valid split."""
        g, h, c = hist[..., 0], hist[..., 1], hist[..., 2]
        tg, th, tc = g.sum(1), h.sum(1), c.sum(1)          # (F,)
        parent = score(tg, th)
        leaf_ok = (tc >= 2.0 * min_data) & feature_mask & depth_ok

        # -- numerical: left = bins [0..t], t in [1, nb-2] ------------------
        cg, ch, cc = jnp.cumsum(g, 1), jnp.cumsum(h, 1), jnp.cumsum(c, 1)
        tpos = jnp.arange(B)[None, :]
        gl, hl, cl = cg, ch, cc
        gr, hr, cr = tg[:, None] - gl, th[:, None] - hl, tc[:, None] - cl
        nvalid = (
            (tpos >= 1)
            & (tpos <= n_bins_arr[:, None] - 2)
            & (cl >= min_data) & (cr >= min_data)
            & (hl >= min_hess) & (hr >= min_hess)
            & (~categorical_arr)[:, None]
            & leaf_ok[:, None]
        )
        ngain = jnp.where(
            nvalid, score(gl, hl) + score(gr, hr) - parent[:, None], NEG
        )
        nbest_t = jnp.argmax(ngain, axis=1)                 # (F,) first max
        nbest_gain = jnp.take_along_axis(ngain, nbest_t[:, None], 1)[:, 0]

        # -- categorical: sorted by g/h ratio, both directions --------------
        bpos = jnp.arange(B)
        present = (c > 0) & (bpos[None, :] >= 1) & (bpos[None, :] < n_bins_arr[:, None])
        ratio = g / (h + l2 + 1e-12)
        kcats = present.sum(1)                              # (F,)
        lim = jnp.minimum(kcats - 1, max_cat_threshold)

        def one_dir(key):
            order = jnp.argsort(key, axis=1)                # (F, B) stable
            g_s = jnp.take_along_axis(g, order, 1)
            h_s = jnp.take_along_axis(h, order, 1)
            c_s = jnp.take_along_axis(c, order, 1)
            cgl = jnp.cumsum(g_s, 1)
            chl = jnp.cumsum(h_s, 1)
            ccl = jnp.cumsum(c_s, 1)
            cgr = tg[:, None] - cgl
            chr_ = th[:, None] - chl
            ccr = tc[:, None] - ccl
            jpos = jnp.arange(B)[None, :]
            cvalid = (
                (jpos < lim[:, None])
                & (ccl >= min_data) & (ccr >= min_data)
                & (chl >= min_hess) & (chr_ >= min_hess)
                & categorical_arr[:, None]
                & leaf_ok[:, None]
            )
            cgain = jnp.where(
                cvalid, score(cgl, chl) + score(cgr, chr_) - parent[:, None], NEG
            )
            jbest = jnp.argmax(cgain, axis=1)
            return order, jbest, jnp.take_along_axis(cgain, jbest[:, None], 1)[:, 0]

        inf = jnp.float32(jnp.inf)
        key_asc = jnp.where(present, ratio, inf)
        key_desc = jnp.where(present, -ratio, inf)
        if ABL_CAT:
            o1, j1, g1 = one_dir(key_asc)
            o2, j2, g2 = one_dir(key_desc)
        else:
            o1 = jnp.broadcast_to(jnp.arange(B)[None, :], (F, B))
            j1 = jnp.zeros(F, jnp.int32); g1 = jnp.full(F, NEG)
            o2, j2, g2 = o1, j1, g1
        use2 = g2 > g1                                      # strict, host parity
        corder = jnp.where(use2[:, None], o2, o1)
        cj = jnp.where(use2, j2, j1)
        cbest_gain = jnp.maximum(g1, g2)

        # -- combine per feature, then first-argmax over features -----------
        fgain = jnp.maximum(nbest_gain, cbest_gain)
        use_cat_f = cbest_gain > nbest_gain
        f_star = jnp.argmax(fgain)
        gain = fgain[f_star]
        is_cat = use_cat_f[f_star] & categorical_arr[f_star]
        t_star = nbest_t[f_star]
        # member mask, True = left
        num_member = jnp.arange(B) <= t_star
        ranks = jnp.zeros(B, jnp.int32).at[corder[f_star]].set(jnp.arange(B, dtype=jnp.int32))
        cat_member = ranks <= cj[f_star]
        member = jnp.where(is_cat, cat_member, num_member)
        # left stats at the chosen cut
        def stats_at(cum_gl, cum_hl, cum_cl, idx):
            return jnp.stack([cum_gl[f_star, idx], cum_hl[f_star, idx], cum_cl[f_star, idx]])

        g_s = jnp.take_along_axis(g, corder, 1)
        h_s = jnp.take_along_axis(h, corder, 1)
        c_s = jnp.take_along_axis(c, corder, 1)
        left_num = stats_at(cg, ch, cc, t_star)
        left_cat = stats_at(jnp.cumsum(g_s, 1), jnp.cumsum(h_s, 1), jnp.cumsum(c_s, 1), cj[f_star])
        left = jnp.where(is_cat, left_cat, left_num)
        total = jnp.stack([tg[f_star], th[f_star], tc[f_star]])
        right = total - left
        thr_bin = jnp.where(is_cat, -1, t_star).astype(jnp.int32)
        return gain, f_star.astype(jnp.int32), thr_bin, is_cat, member, left, right

    # -- root ----------------------------------------------------------------
    if ABL_ROOT:
        hist0 = _hist_masked(bins, grad, hess, sample_mask, B)
        root_stats = jnp.stack([hist0[0, :, 0].sum(), hist0[0, :, 1].sum(), hist0[0, :, 2].sum()])
        depth_ok0 = jnp.asarray(0 < depth_limit)
        bg0, bf0, bt0, bic0, bm0, bl0, br0 = best_split(hist0, depth_ok0)
    else:
        hist0 = jnp.zeros((F, B, 3), jnp.float32) + grad[0] * 1e-20
        root_stats = jnp.stack([hist0[0, :, 0].sum(), hist0[0, :, 1].sum(), hist0[0, :, 2].sum()])
        depth_ok0 = jnp.asarray(0 < depth_limit)
        bg0 = jnp.float32(1.0); bf0 = jnp.int32(0); bt0 = jnp.int32(1)
        bic0 = jnp.asarray(False); bm0 = jnp.zeros(B, bool).at[0].set(True)
        bl0 = jnp.full(3, 60.0); br0 = jnp.full(3, 60.0)

    state = dict(
        assign=jnp.zeros(bins.shape[0], jnp.int32),
        hists=jnp.zeros((L, F, B, 3), jnp.float32).at[0].set(hist0),
        stats=jnp.zeros((L, 3), jnp.float32).at[0].set(root_stats),
        depths=jnp.zeros(L, jnp.int32),
        best_gain=jnp.full(L, NEG).at[0].set(bg0),
        best_feat=jnp.zeros(L, jnp.int32).at[0].set(bf0),
        best_bin=jnp.zeros(L, jnp.int32).at[0].set(bt0),
        best_is_cat=jnp.zeros(L, bool).at[0].set(bic0),
        best_member=jnp.zeros((L, B), bool).at[0].set(bm0),
        best_left=jnp.zeros((L, 3), jnp.float32).at[0].set(bl0),
        best_right=jnp.zeros((L, 3), jnp.float32).at[0].set(br0),
        node_feat=jnp.zeros(L, jnp.int32),
        node_bin=jnp.zeros(L, jnp.int32),
        node_is_cat=jnp.zeros(L, bool),
        node_gain=jnp.zeros(L, jnp.float32),
        node_value=jnp.zeros(L, jnp.float32),
        node_count=jnp.zeros(L, jnp.int32),
        node_left=jnp.full(L, -(2 ** 30), jnp.int32),
        node_right=jnp.full(L, -(2 ** 30), jnp.int32),
        node_member=jnp.zeros((L, B), bool),
        slot_parent=jnp.full(L, -1, jnp.int32),
        slot_side=jnp.zeros(L, jnp.int32),
        n_leaves=jnp.int32(1),
        n_nodes=jnp.int32(0),
        done=jnp.asarray(False),
        step=jnp.int32(0),
    )

    gain_floor = jnp.maximum(min_gain, 0.0)

    def body(st):
        s = jnp.argmax(st["best_gain"]).astype(jnp.int32)
        do = (~st["done"]) & (st["best_gain"][s] > gain_floor)

        def sel(new, old):
            return jnp.where(do, new, old)

        node_id = st["n_nodes"]
        new_slot = st["n_leaves"]

        # record node (writes masked by `do` via sel on the whole array)
        st["node_feat"] = sel(st["node_feat"].at[node_id].set(st["best_feat"][s]), st["node_feat"])
        st["node_bin"] = sel(st["node_bin"].at[node_id].set(st["best_bin"][s]), st["node_bin"])
        st["node_is_cat"] = sel(st["node_is_cat"].at[node_id].set(st["best_is_cat"][s]), st["node_is_cat"])
        st["node_gain"] = sel(st["node_gain"].at[node_id].set(st["best_gain"][s]), st["node_gain"])
        st["node_value"] = sel(
            st["node_value"].at[node_id].set(leaf_out(st["stats"][s, 0], st["stats"][s, 1])),
            st["node_value"],
        )
        st["node_count"] = sel(
            st["node_count"].at[node_id].set(st["stats"][s, 2].astype(jnp.int32)),
            st["node_count"],
        )
        st["node_member"] = sel(st["node_member"].at[node_id].set(st["best_member"][s]), st["node_member"])

        # patch parent pointer (skip for root: parent == -1 -> drop)
        p = st["slot_parent"][s]
        side = st["slot_side"][s]
        lidx = jnp.where(do & (p >= 0) & (side == 0), p, L + 7)
        ridx = jnp.where(do & (p >= 0) & (side == 1), p, L + 7)
        st["node_left"] = st["node_left"].at[lidx].set(node_id, mode="drop")
        st["node_right"] = st["node_right"].at[ridx].set(node_id, mode="drop")
        st["slot_parent"] = sel(
            st["slot_parent"].at[s].set(node_id).at[new_slot].set(node_id),
            st["slot_parent"],
        )
        st["slot_side"] = sel(
            st["slot_side"].at[s].set(0).at[new_slot].set(1), st["slot_side"]
        )

        # route rows: member True = stay left (slot s), else new_slot
        if ABL_ROUTE:
            fcol = jnp.take(bins, st["best_feat"][s], axis=1)
            go_left = st["best_member"][s][fcol]
            st["assign"] = sel(
                jnp.where((st["assign"] == s) & ~go_left, new_slot, st["assign"]).astype(jnp.int32),
                st["assign"],
            )
        else:
            st["assign"] = sel((st["assign"] + new_slot * 0).astype(jnp.int32), st["assign"])

        # child histograms: scatter the SMALLER child, subtract for sibling
        lcnt = st["best_left"][s, 2]
        rcnt = st["best_right"][s, 2]
        small_is_left = lcnt <= rcnt
        small_slot = jnp.where(small_is_left, s, new_slot)
        if ABL_HIST:
            small_hist = _hist_masked(
                bins, grad, hess, sample_mask & (st["assign"] == small_slot), B
            )
        else:
            small_hist = st["hists"][s] * 0.5
        big_hist = st["hists"][s] - small_hist
        left_hist = jnp.where(small_is_left, small_hist, big_hist)
        right_hist = jnp.where(small_is_left, big_hist, small_hist)
        st["hists"] = sel(
            st["hists"].at[s].set(left_hist).at[new_slot].set(right_hist),
            st["hists"],
        )
        st["stats"] = sel(
            st["stats"].at[s].set(st["best_left"][s]).at[new_slot].set(st["best_right"][s]),
            st["stats"],
        )
        depth = st["depths"][s] + 1
        st["depths"] = sel(
            st["depths"].at[s].set(depth).at[new_slot].set(depth), st["depths"]
        )

        # recompute best splits for the two children (one vmapped instance
        # of best_split keeps the compiled program half the size)
        depth_ok = depth < depth_limit
        if ABL_CHILD:
            cg_, cf_, ct_, cic_, cm_, cl_, cr_ = jax.vmap(
                lambda hh: best_split(hh, depth_ok)
            )(jnp.stack([left_hist, right_hist]))
        else:
            z = left_hist[0, 0, 0] * 1e-20
            cg_ = jnp.stack([z + 1.0, z + 1.0])
            cf_ = jnp.zeros(2, jnp.int32); ct_ = jnp.ones(2, jnp.int32)
            cic_ = jnp.zeros(2, bool)
            cm_ = jnp.zeros((2, B), bool).at[:, 0].set(True)
            cl_ = jnp.full((2, 3), 60.0); cr_ = jnp.full((2, 3), 60.0)
        st["best_gain"] = sel(st["best_gain"].at[s].set(cg_[0]).at[new_slot].set(cg_[1]), st["best_gain"])
        st["best_feat"] = sel(st["best_feat"].at[s].set(cf_[0]).at[new_slot].set(cf_[1]), st["best_feat"])
        st["best_bin"] = sel(st["best_bin"].at[s].set(ct_[0]).at[new_slot].set(ct_[1]), st["best_bin"])
        st["best_is_cat"] = sel(st["best_is_cat"].at[s].set(cic_[0]).at[new_slot].set(cic_[1]), st["best_is_cat"])
        st["best_member"] = sel(st["best_member"].at[s].set(cm_[0]).at[new_slot].set(cm_[1]), st["best_member"])
        st["best_left"] = sel(st["best_left"].at[s].set(cl_[0]).at[new_slot].set(cl_[1]), st["best_left"])
        st["best_right"] = sel(st["best_right"].at[s].set(cr_[0]).at[new_slot].set(cr_[1]), st["best_right"])

        st["n_leaves"] = sel(st["n_leaves"] + 1, st["n_leaves"])
        st["n_nodes"] = sel(st["n_nodes"] + 1, st["n_nodes"])
        st["done"] = st["done"] | ~do
        st["step"] = st["step"] + 1
        return st

    # while_loop (not fori): a tree that converges at 5 leaves must not pay
    # for num_leaves-1 full-data histogram steps of masked no-ops
    state = jax.lax.while_loop(
        lambda st: (st["step"] < L - 1) & ~st["done"], body, state
    )

    # -- finalize ------------------------------------------------------------
    slots = jnp.arange(L)
    live = slots < state["n_leaves"]
    leaf_values = jnp.where(
        live, leaf_out(state["stats"][:, 0], state["stats"][:, 1]) * learning_rate, 0.0
    ).astype(jnp.float32)
    leaf_counts = jnp.where(live, state["stats"][:, 2], 0.0)

    # patch leaf references (~slot) into the child arrays
    pmask = live & (state["slot_parent"] >= 0)
    lpatch = jnp.where(pmask & (state["slot_side"] == 0), state["slot_parent"], L + 7)
    rpatch = jnp.where(pmask & (state["slot_side"] == 1), state["slot_parent"], L + 7)
    node_left = state["node_left"].at[lpatch].set(~slots, mode="drop")
    node_right = state["node_right"].at[rpatch].set(~slots, mode="drop")

    packed = jnp.concatenate([
        jnp.stack([state["n_nodes"].astype(jnp.float32),
                   state["n_leaves"].astype(jnp.float32)]),
        state["node_feat"].astype(jnp.float32),
        state["node_bin"].astype(jnp.float32),
        state["node_is_cat"].astype(jnp.float32),
        state["node_gain"],
        state["node_value"],
        state["node_count"].astype(jnp.float32),
        node_left.astype(jnp.float32),
        node_right.astype(jnp.float32),
        state["node_member"].astype(jnp.float32).reshape(-1),
        leaf_values,
        leaf_counts,
    ])
    return packed, leaf_values, state["assign"]


