"""Scaling bisect of grow_tree_fused: vary L, B, n; sentinel op tracks
tunnel mood so slow-RTT windows are visible in the numbers."""

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.gbdt.binning import BinMapper
from mmlspark_tpu.gbdt.tree import GrowConfig, grow_tree_packed
from bench import make_adult_like


def timeit(fn, n=3, warmup=1):
    for _ in range(warmup):
        jax.block_until_ready(fn())
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


sent = jax.device_put(jnp.zeros(8))
f_sent = jax.jit(lambda a: a + 1)


def sentinel():
    return timeit(lambda: f_sent(sent), n=3) * 1e3


x, y, cat_idx = make_adult_like()
x = x[:39073]

rng = np.random.default_rng(0)


def run(max_bin, num_leaves, n_rows, cats=True):
    xi = x[:n_rows]
    binner = BinMapper(max_bin, cat_idx if cats else [])
    binner.fit(xi)
    rb = binner.transform(xi)
    pad = (-len(rb)) % 1024
    rb = np.concatenate([rb, np.zeros((pad, 14), rb.dtype)]).astype(np.int32)
    n = len(rb)
    B = binner.max_n_bins
    bins_dev = jax.device_put(rb)
    g = jax.device_put(rng.normal(size=n).astype(np.float32))
    h = jax.device_put((np.abs(rng.normal(size=n)) + 0.1).astype(np.float32))
    mask = jax.device_put(np.arange(n) < n_rows)
    nb = jnp.asarray(np.asarray(binner.n_bins, np.int32))
    cat = jnp.asarray(np.asarray([binner.is_categorical(j) for j in range(14)], bool))
    fm = jnp.asarray(np.ones(14, bool))
    cfg = GrowConfig(num_leaves=num_leaves, max_depth=-1, min_data_in_leaf=20,
                     min_sum_hessian_in_leaf=1e-3, lambda_l1=0.0, lambda_l2=0.0,
                     min_gain_to_split=0.0, learning_rate=0.1)

    t = timeit(lambda: grow_tree_packed(bins_dev, g, h, mask, nb, cat, fm, B, cfg)[0])
    print(f"B={B:<4} L={num_leaves:<3} n={n:<6} cats={cats}: "
          f"{t*1e3:8.2f} ms   [sentinel {sentinel():.2f} ms]")


print(f"[sentinel {sentinel():.2f} ms]")
run(255, 31, 39073)          # baseline config
run(255, 2, 39073)           # 1 split: fixed cost
run(255, 8, 39073)
run(255, 16, 39073)
run(63, 31, 39073)           # smaller B
run(255, 31, 8000)           # fewer rows
run(255, 31, 2000)
run(255, 31, 39073, cats=False)  # no categorical features
