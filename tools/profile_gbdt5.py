"""Ablate grow-tree body components to find the 187ms/tree cost.

All variants run a FIXED 30 steps (done-flag ignored) so timing compares
structure, not convergence.
"""

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.gbdt.binning import BinMapper
from bench import make_adult_like

x, y, cat_idx = make_adult_like()
n0 = int(len(y) * 0.8)
binner = BinMapper(255, cat_idx)
binner.fit(x[:n0])
rb = binner.transform(x[:n0])
pad = (-len(rb)) % 1024
rb = np.concatenate([rb, np.zeros((pad, 14), rb.dtype)]).astype(np.int32)
n, F = rb.shape
B = int(max(binner.n_bins))
L = 31
bins_h = rb
bins = jax.device_put(rb)
rng = np.random.default_rng(0)
g0 = jax.device_put(rng.normal(size=n).astype(np.float32))
h0 = jax.device_put((np.abs(rng.normal(size=n)) + 0.1).astype(np.float32))
mask = jax.device_put(np.arange(n) < n0)
n_bins_arr = jnp.asarray(np.asarray(binner.n_bins, np.int32))
categorical_arr = jnp.asarray(np.asarray([binner.is_categorical(j) for j in range(14)], bool))
feature_mask = jnp.asarray(np.ones(14, bool))
min_data, min_hess, l1, l2 = jnp.float32(20), jnp.float32(1e-3), jnp.float32(0.0), jnp.float32(0.0)
NEG = jnp.float32(-jnp.inf)


def make_body(do_cat=True, do_hist=True, do_child=True, do_route=True):
    def thresh(g):
        return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)

    def score(g, h):
        t = thresh(g)
        return t * t / jnp.maximum(h + l2, 1e-35)

    def hist_fn(grad, hess, m):
        gg = jnp.where(m, grad, 0.0)
        hh = jnp.where(m, hess, 0.0)
        c = m.astype(jnp.float32)
        idx = bins + jnp.arange(F, dtype=jnp.int32)[None, :] * B
        upd = jnp.stack([jnp.broadcast_to(gg[:, None], (n, F)),
                         jnp.broadcast_to(hh[:, None], (n, F)),
                         jnp.broadcast_to(c[:, None], (n, F))], axis=-1)
        flat = jnp.zeros((F * B, 3), jnp.float32).at[idx.reshape(-1)].add(upd.reshape(-1, 3))
        return flat.reshape(F, B, 3)

    def best_split(hist, depth_ok):
        g, h, c = hist[..., 0], hist[..., 1], hist[..., 2]
        tg, th, tc = g.sum(1), h.sum(1), c.sum(1)
        parent = score(tg, th)
        leaf_ok = (tc >= 2.0 * min_data) & feature_mask & depth_ok
        cg, ch, cc = jnp.cumsum(g, 1), jnp.cumsum(h, 1), jnp.cumsum(c, 1)
        tpos = jnp.arange(B)[None, :]
        gl, hl, cl = cg, ch, cc
        gr, hr, cr = tg[:, None] - gl, th[:, None] - hl, tc[:, None] - cl
        nvalid = ((tpos >= 1) & (tpos <= n_bins_arr[:, None] - 2)
                  & (cl >= min_data) & (cr >= min_data)
                  & (hl >= min_hess) & (hr >= min_hess)
                  & (~categorical_arr)[:, None] & leaf_ok[:, None])
        ngain = jnp.where(nvalid, score(gl, hl) + score(gr, hr) - parent[:, None], NEG)
        nbest_t = jnp.argmax(ngain, axis=1)
        nbest_gain = jnp.take_along_axis(ngain, nbest_t[:, None], 1)[:, 0]

        if do_cat:
            bpos = jnp.arange(B)
            present = (c > 0) & (bpos[None, :] >= 1) & (bpos[None, :] < n_bins_arr[:, None])
            ratio = g / (h + l2 + 1e-12)
            kcats = present.sum(1)
            lim = jnp.minimum(kcats - 1, 32)

            def one_dir(key):
                order = jnp.argsort(key, axis=1)
                g_s = jnp.take_along_axis(g, order, 1)
                h_s = jnp.take_along_axis(h, order, 1)
                c_s = jnp.take_along_axis(c, order, 1)
                cgl = jnp.cumsum(g_s, 1)
                chl = jnp.cumsum(h_s, 1)
                ccl = jnp.cumsum(c_s, 1)
                cgr = tg[:, None] - cgl
                chr_ = th[:, None] - chl
                ccr = tc[:, None] - ccl
                jpos = jnp.arange(B)[None, :]
                cvalid = ((jpos < lim[:, None]) & (ccl >= min_data) & (ccr >= min_data)
                          & (chl >= min_hess) & (chr_ >= min_hess)
                          & categorical_arr[:, None] & leaf_ok[:, None])
                cgain = jnp.where(cvalid, score(cgl, chl) + score(cgr, chr_) - parent[:, None], NEG)
                jbest = jnp.argmax(cgain, axis=1)
                return order, jbest, jnp.take_along_axis(cgain, jbest[:, None], 1)[:, 0]

            inf = jnp.float32(jnp.inf)
            o1, j1, g1 = one_dir(jnp.where(present, ratio, inf))
            o2, j2, g2 = one_dir(jnp.where(present, -ratio, inf))
            use2 = g2 > g1
            corder = jnp.where(use2[:, None], o2, o1)
            cj = jnp.where(use2, j2, j1)
            cbest_gain = jnp.maximum(g1, g2)
        else:
            corder = jnp.broadcast_to(jnp.arange(B)[None, :], (F, B))
            cj = jnp.zeros(F, jnp.int32)
            cbest_gain = jnp.full(F, NEG)

        fgain = jnp.maximum(nbest_gain, cbest_gain)
        use_cat_f = cbest_gain > nbest_gain
        f_star = jnp.argmax(fgain)
        gain = fgain[f_star]
        is_cat = use_cat_f[f_star] & categorical_arr[f_star]
        t_star = nbest_t[f_star]
        num_member = jnp.arange(B) <= t_star
        ranks = jnp.zeros(B, jnp.int32).at[corder[f_star]].set(jnp.arange(B, dtype=jnp.int32))
        cat_member = ranks <= cj[f_star]
        member = jnp.where(is_cat, cat_member, num_member)
        g_s = jnp.take_along_axis(g, corder, 1)
        h_s = jnp.take_along_axis(h, corder, 1)
        c_s = jnp.take_along_axis(c, corder, 1)
        left_num = jnp.stack([cg[f_star, t_star], ch[f_star, t_star], cc[f_star, t_star]])
        cjf = cj[f_star]
        left_cat = jnp.stack([jnp.cumsum(g_s, 1)[f_star, cjf], jnp.cumsum(h_s, 1)[f_star, cjf], jnp.cumsum(c_s, 1)[f_star, cjf]])
        left = jnp.where(is_cat, left_cat, left_num)
        total = jnp.stack([tg[f_star], th[f_star], tc[f_star]])
        right = total - left
        return gain, f_star.astype(jnp.int32), member, left, right

    def grow(grad, hess):
        hist0 = hist_fn(grad, hess, mask)
        bg0, bf0, bm0, bl0, br0 = best_split(hist0, jnp.asarray(True))
        state = dict(
            assign=jnp.zeros(n, jnp.int32),
            hists=jnp.zeros((L, F, B, 3), jnp.float32).at[0].set(hist0),
            best_gain=jnp.full(L, NEG).at[0].set(bg0),
            best_feat=jnp.zeros(L, jnp.int32).at[0].set(bf0),
            best_member=jnp.zeros((L, B), bool).at[0].set(bm0),
            best_left=jnp.zeros((L, 3), jnp.float32).at[0].set(bl0),
            best_right=jnp.zeros((L, 3), jnp.float32).at[0].set(br0),
            n_leaves=jnp.int32(1),
            step=jnp.int32(0),
        )

        def body(st):
            s = jnp.argmax(st["best_gain"]).astype(jnp.int32)
            new_slot = st["n_leaves"]
            if do_route:
                fcol = jnp.take(bins, st["best_feat"][s], axis=1)
                go_left = st["best_member"][s][fcol]
                st["assign"] = jnp.where((st["assign"] == s) & ~go_left, new_slot, st["assign"]).astype(jnp.int32)
            if do_hist:
                lcnt = st["best_left"][s, 2]
                rcnt = st["best_right"][s, 2]
                small_is_left = lcnt <= rcnt
                small_slot = jnp.where(small_is_left, s, new_slot)
                small_hist = hist_fn(grad, hess, mask & (st["assign"] == small_slot))
                big_hist = st["hists"][s] - small_hist
                left_hist = jnp.where(small_is_left, small_hist, big_hist)
                right_hist = jnp.where(small_is_left, big_hist, small_hist)
            else:
                left_hist = st["hists"][s] * 0.5
                right_hist = st["hists"][s] * 0.5
            st["hists"] = st["hists"].at[s].set(left_hist).at[new_slot].set(right_hist)
            if do_child:
                cg_, cf_, cm_, cl_, cr_ = jax.vmap(lambda hh: best_split(hh, jnp.asarray(True)))(jnp.stack([left_hist, right_hist]))
                st["best_gain"] = st["best_gain"].at[s].set(cg_[0]).at[new_slot].set(cg_[1])
                st["best_feat"] = st["best_feat"].at[s].set(cf_[0]).at[new_slot].set(cf_[1])
                st["best_member"] = st["best_member"].at[s].set(cm_[0]).at[new_slot].set(cm_[1])
                st["best_left"] = st["best_left"].at[s].set(cl_[0]).at[new_slot].set(cl_[1])
                st["best_right"] = st["best_right"].at[s].set(cr_[0]).at[new_slot].set(cr_[1])
            else:
                st["best_gain"] = st["best_gain"].at[s].set(left_hist[0, 0, 0] * 1e-20)
            st["n_leaves"] = st["n_leaves"] + 1
            st["step"] = st["step"] + 1
            return st

        state = jax.lax.while_loop(lambda st: st["step"] < L - 1, body, state)
        return state["best_gain"]

    return grow


def time_variant(label, **kw):
    grow = make_body(**kw)

    @jax.jit
    def prog(g, h):
        def body(carry, _):
            out = grow(g + carry * 1e-20, h)
            return out[0], None
        out, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=5)
        return out

    r = prog(g0, h0)
    jax.block_until_ready(r)
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(prog(g0, h0))
        ts.append(time.perf_counter() - t0)
    t = float(np.median(ts))
    print(f"{label}: {t/5*1e3:8.2f} ms/tree")


time_variant("full                    ")
time_variant("no categorical argsorts ", do_cat=False)
time_variant("no child best_split     ", do_child=False)
time_variant("no hist (fake halves)   ", do_hist=False)
time_variant("no row routing          ", do_route=False)
time_variant("no hist no child        ", do_hist=False, do_child=False)
