"""Per-phase GBDT fit profiler (VERDICT r3 weak#1: nobody has profiled it).

Separates: (a) dispatch round-trip latency through the chip tunnel,
(b) histogram kernel cost (scatter-add vs one-hot matmul), (c) the fused
grower's single-tree cost, (d) end-to-end fit. Results go in BASELINE.md.

Run: python tools/profile_gbdt.py
"""

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, n=5, warmup=1):
    for _ in range(warmup):
        jax.block_until_ready(fn())
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def main():
    print("devices:", jax.devices())

    # (a) dispatch round-trip: trivial jit op, blocking each call
    tiny = jnp.zeros(8, jnp.float32)
    f_triv = jax.jit(lambda a: a + 1)
    rt = timeit(lambda: f_triv(tiny), n=10)
    print(f"dispatch_roundtrip_ms: {rt*1e3:.2f}")

    # Adult-shape data
    from bench import make_adult_like
    from mmlspark_tpu.gbdt.binning import BinMapper

    x, y, cat_idx = make_adult_like()
    n0 = int(len(y) * 0.8)
    x, y = x[:n0], y[:n0]
    binner = BinMapper(255, cat_idx)
    binner.fit(x)
    bins = binner.transform(x)
    pad = (-len(y)) % 1024
    bins = np.concatenate([bins, np.zeros((pad, bins.shape[1]), bins.dtype)])
    n, f = bins.shape
    B = 256
    print(f"n={n} f={f} B={B} per-feature bins={list(binner.n_bins)}")

    bins_dev = jax.device_put(bins.astype(np.int32))
    g = jax.device_put(np.random.default_rng(0).normal(size=n).astype(np.float32))
    h = jax.device_put(np.abs(np.random.default_rng(1).normal(size=n)).astype(np.float32) + 0.1)
    mask = jax.device_put(np.arange(n) < n0)

    # (b) histogram kernels
    from mmlspark_tpu.gbdt.compute import leaf_histogram

    t_scatter = timeit(lambda: leaf_histogram(bins_dev, g, h, mask, num_bins=B))
    print(f"hist_scatter_ms: {t_scatter*1e3:.2f}")

    @jax.jit
    def hist_matmul(bins, grad, hess, mask):
        gm = jnp.where(mask, grad, 0.0).astype(jnp.float32)
        hm = jnp.where(mask, hess, 0.0).astype(jnp.float32)
        cm = mask.astype(jnp.float32)
        vals = jnp.stack([gm, hm, cm], axis=1)  # (n, 3)

        def chunk(carry, se):
            b_c, v_c = se  # (C, F) int32, (C, 3)
            oh = (b_c[:, :, None] == jnp.arange(B, dtype=jnp.int32)).astype(jnp.float32)
            hist = jnp.einsum("cfb,cv->fbv", oh, v_c,
                              preferred_element_type=jnp.float32)
            return carry + hist, None

        C = 1024
        nb = bins.shape[0] // C
        out, _ = jax.lax.scan(
            chunk,
            jnp.zeros((f, B, 3), jnp.float32),
            (bins.reshape(nb, C, f), vals.reshape(nb, C, 3)),
        )
        return out

    t_mm = timeit(lambda: hist_matmul(bins_dev, g, h, mask))
    print(f"hist_matmul_ms: {t_mm*1e3:.2f}")
    a = np.asarray(leaf_histogram(bins_dev, g, h, mask, num_bins=B))
    b = np.asarray(hist_matmul(bins_dev, g, h, mask))
    print("hist parity max abs diff:", float(np.abs(a - b).max()))

    # (c) fused grower, one tree
    from mmlspark_tpu.gbdt.tree import GrowConfig, grow_tree_packed

    cfg = GrowConfig(num_leaves=31, max_depth=-1, min_data_in_leaf=20,
                     min_sum_hessian_in_leaf=1e-3, lambda_l1=0.0, lambda_l2=0.0,
                     min_gain_to_split=0.0, learning_rate=0.1)
    n_bins_dev = jnp.asarray(np.asarray(binner.n_bins, np.int32))
    cat_dev = jnp.asarray(np.asarray([binner.is_categorical(j) for j in range(f)], bool))
    fmask = jnp.asarray(np.ones(f, bool))

    def one_tree():
        p, lv, a = grow_tree_packed(bins_dev, g, h, mask, n_bins_dev, cat_dev,
                                    fmask, B, cfg)
        return p

    t_tree = timeit(one_tree, n=5)
    print(f"grow_tree_ms: {t_tree*1e3:.2f}  (x100 trees = {t_tree*100:.2f}s)")

    # (d) end-to-end fit (warm cache)
    from bench import bench_gbdt
    secs, auc = bench_gbdt()
    print(f"fit_seconds: {secs:.2f} auc: {auc:.4f}")


if __name__ == "__main__":
    main()
