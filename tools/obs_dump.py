"""obs_dump: snapshot a serving server's whole observability surface.

One command fetches `/metrics?exemplars=1`, `/healthz`, and every
`/debug/*` endpoint — at cluster scope when the target is a federated
gateway (`--scope cluster`, the default tries cluster and falls back to
local) — and writes a single timestamped JSON bundle for offline triage
or attaching to a bug report:

    python tools/obs_dump.py --host 127.0.0.1 --port 8080
    python tools/obs_dump.py --port 8080 --out triage/ --scope local
    python tools/obs_dump.py --port 8080 --trace-id 9f2c...   # + one tree

The bundle carries every endpoint's payload (or its error — a dead
endpoint never aborts the dump; partial evidence beats none), the target
address, and the capture timestamps. Reads only; safe against production.
"""

import argparse
import http.client
import json
import sys
import time
from datetime import datetime, timezone


def fetch(host, port, path, timeout):
    """(ok, payload) — payload is parsed JSON, exposition text, or the
    error string. Never raises: the dump must survive dead endpoints."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read()
        ctype = resp.getheader("Content-Type") or ""
        if resp.status != 200:
            return False, f"HTTP {resp.status}: {body[:200]!r}"
        if "json" in ctype:
            return True, json.loads(body.decode("utf-8"))
        return True, body.decode("utf-8", "replace")
    except (OSError, http.client.HTTPException, ValueError) as e:
        return False, repr(e)
    finally:
        conn.close()


def snapshot(host, port, scope="auto", trace_id=None, timeout=10.0):
    """The bundle dict: every observability endpoint, captured once."""
    cluster = "?scope=cluster"
    endpoints = {
        "metrics": "/metrics?exemplars=1",
        "healthz": "/healthz",
        "debug_flight": "/debug/flight",
        "debug_memory": "/debug/memory",
        "debug_trace": "/debug/trace",
    }
    if trace_id:
        endpoints["trace_tree"] = f"/debug/trace?trace_id={trace_id}"
    bundle = {
        "target": f"{host}:{port}",
        "captured_utc": datetime.now(timezone.utc).isoformat(),
        "scope": scope,
        "endpoints": {},
        "errors": {},
    }
    for name, path in endpoints.items():
        use = path
        if scope in ("auto", "cluster") and name.startswith(("debug_", "trace_")):
            sep = "&" if "?" in path else "?"
            use = path + sep + cluster.lstrip("?")
        t0 = time.monotonic()
        ok, payload = fetch(host, port, use, timeout)
        if not ok and scope == "auto" and use != path:
            # not a federated gateway (or fan-out refused): local payload
            use = path
            ok, payload = fetch(host, port, use, timeout)
        entry = {
            "path": use,
            "fetch_seconds": round(time.monotonic() - t0, 4),
        }
        if ok:
            entry["payload"] = payload
            bundle["endpoints"][name] = entry
        else:
            entry["error"] = payload
            bundle["errors"][name] = entry
    return bundle


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Snapshot /metrics + /healthz + /debug/* into one "
        "timestamped JSON bundle for offline triage."
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument(
        "--scope", choices=("auto", "cluster", "local"), default="auto",
        help="cluster: require ?scope=cluster fan-out; local: never ask "
        "for it; auto (default): try cluster, fall back to local",
    )
    ap.add_argument(
        "--trace-id", default=None,
        help="also capture /debug/trace?trace_id= for this trace",
    )
    ap.add_argument(
        "--timeout", type=float, default=10.0,
        help="per-endpoint fetch timeout in seconds",
    )
    ap.add_argument(
        "--out", default=".",
        help="output directory (or '-' to print the bundle to stdout)",
    )
    args = ap.parse_args(argv)
    bundle = snapshot(
        args.host, args.port, scope=args.scope,
        trace_id=args.trace_id, timeout=args.timeout,
    )
    if args.out == "-":
        json.dump(bundle, sys.stdout, indent=2, sort_keys=True)
        print()
        return 0
    import os

    stamp = datetime.now(timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    path = os.path.join(
        args.out, f"obs_dump_{args.host}_{args.port}_{stamp}.json"
    )
    os.makedirs(args.out, exist_ok=True)
    with open(path, "w") as f:
        json.dump(bundle, f, indent=2, sort_keys=True)
    captured = sorted(bundle["endpoints"])
    failed = sorted(bundle["errors"])
    print(f"wrote {path} ({len(captured)} endpoints"
          + (f", {len(failed)} failed: {failed}" if failed else "")
          + ")")
    return 0 if captured else 1


if __name__ == "__main__":
    sys.exit(main())
