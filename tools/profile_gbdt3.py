"""Bisect why large-row ops sometimes cost ~113ms."""

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, n=5, warmup=2, label=""):
    for _ in range(warmup):
        jax.block_until_ready(fn())
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        ts.append(time.perf_counter() - t0)
    print(f"{label}: median {np.median(ts)*1e3:.3f} ms  all={[f'{t*1e3:.2f}' for t in ts]}")


N = 39936
rng = np.random.default_rng(0)
a_np = rng.normal(size=N).astype(np.float32)
a = jax.device_put(a_np)

f_add = jax.jit(lambda x: x + 1.0)
timeit(lambda: f_add(a), label="add1_39936")

b = jax.device_put(rng.normal(size=1024).astype(np.float32))
timeit(lambda: f_add(b), label="add1_1024")

c = jax.device_put(rng.normal(size=(39936, 14)).astype(np.float32))
f_sum = jax.jit(lambda x: x.sum())
timeit(lambda: f_sum(c), label="sum_39936x14")

# int32 gather like route
fcol = jax.device_put(rng.integers(0, 256, N).astype(np.int32))
member = jax.device_put(np.ones(256, bool))
f_gather = jax.jit(lambda m, i: m[i])
timeit(lambda: f_gather(member, fcol), label="gather_39936")

# bool mask out
mask = jax.device_put(np.ones(N, bool))
f_where = jax.jit(lambda x, m: jnp.where(m, x, 0.0))
timeit(lambda: f_where(a, mask), label="where_39936")

# returning large vs small
f_small = jax.jit(lambda x: (x + 1.0).sum())
timeit(lambda: f_small(a), label="add_reduce_to_scalar")
