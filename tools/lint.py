"""graftcheck CLI: framework-aware static analysis for mmlspark_tpu.

Run:  python tools/lint.py [path]            # full pass, exit 1 on findings
      python tools/lint.py --list-rules      # rule catalog
      python tools/lint.py --select jit-host-item,jit-print
      python tools/lint.py --disable docs-drift

The same pass gates tier-1 through
tests/test_static_analysis.py::test_package_lint_clean; see
docs/static-analysis.md for the rule families and the
`# graftcheck: ignore[rule]` suppression syntax.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftcheck", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "path", nargs="?", default=None,
        help="package dir or repo root to lint (default: repo containing tools/)",
    )
    parser.add_argument(
        "--select", default=None,
        help="comma-separated rule ids to run exclusively",
    )
    parser.add_argument(
        "--disable", default=None,
        help="comma-separated rule ids to skip (adds to [tool.graftcheck] disable)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog and exit"
    )
    args = parser.parse_args(argv)

    from mmlspark_tpu.analysis import RULES, run_all
    from mmlspark_tpu.analysis.config import find_repo_root

    if args.list_rules:
        width = max(len(r) for r in RULES)
        for rule, desc in sorted(RULES.items()):
            print(f"{rule:<{width}}  {desc}")
        return 0

    root = None
    if args.path:
        root = find_repo_root(args.path)
        if root is None:
            print(f"error: no pyproject.toml above {args.path}", file=sys.stderr)
            return 2

    select = args.select.split(",") if args.select else None
    disable = args.disable.split(",") if args.disable else None
    try:
        findings = run_all(root=root, select=select, disable=disable)
    except ValueError as e:
        # unknown rule id: a usage error (exit 2), distinct from findings (1)
        print(f"error: {e}", file=sys.stderr)
        return 2
    for f in findings:
        print(f)
    if findings:
        print(f"\ngraftcheck: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print("graftcheck: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
