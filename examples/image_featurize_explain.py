"""Example: the image pipeline end-to-end — download a zoo model, featurize
images through the truncated network, train a classical learner on the
features, and explain a prediction with LIME.

Run:  python examples/image_featurize_explain.py
(Set JAX_PLATFORMS=cpu on machines without an accelerator.)

This is the reference's CIFAR transfer-learning + ImageLIME story on the
TPU-native stack.
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType
from mmlspark_tpu.core.pipeline import PipelineModel, Transformer
from mmlspark_tpu.core.schema import make_image_row
from mmlspark_tpu.downloader import ModelDownloader
from mmlspark_tpu.images import ImageFeaturizer, ImageLIME

PATCH = 8


def make_images(n, seed=0):
    """Two-patch XOR task images (the zoo model's training distribution)."""
    rng = np.random.default_rng(seed)
    imgs = rng.integers(0, 60, size=(n, 32, 32, 3)).astype(np.uint8)
    p1 = rng.integers(0, 2, n).astype(bool)
    p2 = rng.integers(0, 2, n).astype(bool)
    imgs[p1, 4:4 + PATCH, 4:4 + PATCH] = 220
    imgs[p2, 20:20 + PATCH, 20:20 + PATCH] = 220
    return imgs, (p1 ^ p2).astype(np.float64)


def to_df(imgs):
    rows = np.empty(len(imgs), dtype=object)
    for i, im in enumerate(imgs):
        rows[i] = make_image_row(im, f"img{i}")
    return DataFrame({"image": Column(rows, DataType.STRUCT)})


def main() -> None:
    with tempfile.TemporaryDirectory() as local_repo:
        _run(local_repo)


def _run(local_repo: str) -> None:
    # -- download a model from the zoo ---------------------------------------
    downloader = ModelDownloader(local_repo)
    schema = downloader.download_by_name("ConvNet")
    print(f"downloaded {schema.name}_{schema.dataset} "
          f"(sha256 {schema.hash[:12]}..., layers {schema.layer_names})")

    # -- featurize: truncated network (penultimate activations) --------------
    imgs, labels = make_images(300, seed=7)
    df = to_df(imgs)
    featurizer = ImageFeaturizer(
        input_col="image", output_col="features", cut_output_layers=1
    )
    featurizer.set_model(schema)
    feats = featurizer.transform(df)["features"]
    print(f"featurized: {feats.shape}")

    # -- linear probe on the features (transfer learning) --------------------
    design = np.concatenate([feats, np.ones((len(feats), 1))], axis=1)
    coef, *_ = np.linalg.lstsq(design[:200], labels[:200] * 2 - 1, rcond=None)
    acc = ((design[200:] @ coef > 0) == (labels[200:] > 0)).mean()
    print(f"transfer-learning probe accuracy: {acc:.3f} (XOR task — "
          "raw-pixel linear probes sit at ~0.5)")
    assert acc > 0.85

    # -- explain one prediction with LIME ------------------------------------
    full = ImageFeaturizer(input_col="image", output_col="features",
                           cut_output_layers=0)
    full.set_model(schema)

    class Head(Transformer):
        """Class-1 logit margin as the scalar LIME explains."""

        def transform(self, frame):
            s = frame["features"]
            return frame.with_column(
                "prediction", s[:, 1] - s[:, 0], DataType.DOUBLE
            )

        def transform_schema(self, schema):
            return schema

    rng = np.random.default_rng(5)
    one = rng.integers(0, 60, size=(32, 32, 3)).astype(np.uint8)
    one[4:4 + PATCH, 4:4 + PATCH] = 220  # exactly one patch -> class 1
    lime = ImageLIME(model=PipelineModel([full, Head()]),
                     label_col="prediction")
    lime.set_n_samples(120).set_cell_size(8.0)
    out = lime.transform(to_df(one[None]))
    w = out["weights"][0]
    sp = out["superpixels"][0]
    top = sp["clusters"][int(np.argmax(w))]
    xs = [p[0] for p in top]
    ys = [p[1] for p in top]
    print(f"LIME: top superpixel bbox x[{min(xs)},{max(xs)}] "
          f"y[{min(ys)},{max(ys)}] (the informative patch is x,y in [4,12))")
    assert max(xs) < 16 and max(ys) < 16
    print("OK")


if __name__ == "__main__":
    main()
