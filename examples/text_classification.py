"""Example: text classification — TextFeaturizer (tokenize, stop-words,
n-grams, hashing TF-IDF) feeding a classifier, with model statistics.

Run:  python examples/text_classification.py
(Set JAX_PLATFORMS=cpu on machines without an accelerator.)

Mirrors the reference's "TextAnalytics - Amazon Book Reviews" sample
notebook flow (TextFeaturizer -> TrainClassifier -> ComputeModelStatistics).
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.automl.statistics import ComputeModelStatistics
from mmlspark_tpu.core.dataframe import DataFrame, DataType
from mmlspark_tpu.ml import LogisticRegression
from mmlspark_tpu.text.features import TextFeaturizer

POSITIVE = ["great", "excellent", "loved", "wonderful", "amazing", "best"]
NEGATIVE = ["terrible", "awful", "hated", "boring", "worst", "refund"]
FILLER = ["the", "book", "story", "plot", "chapter", "author", "read",
          "pages", "it", "was", "and", "very"]


def make_reviews(n=600, seed=0):
    rng = np.random.default_rng(seed)
    texts, labels = [], []
    for _ in range(n):
        label = rng.integers(0, 2)
        vocab = POSITIVE if label else NEGATIVE
        words = [str(rng.choice(vocab))] + [
            str(rng.choice(FILLER)) for _ in range(rng.integers(4, 10))
        ]
        if rng.random() < 0.3:
            words.append(str(rng.choice(vocab)))
        rng.shuffle(words)
        texts.append(" ".join(words))
        labels.append(float(label))
    return DataFrame.from_dict(
        {"text": np.array(texts, object), "label": np.array(labels)},
        types={"text": DataType.STRING},
    )


def main() -> None:
    df = make_reviews()
    n_train = 450
    train = df.limit(n_train)
    test = df.filter(np.arange(len(df)) >= n_train)

    feats = TextFeaturizer(
        input_col="text", output_col="features", num_features=256,
        use_stop_words_remover=True, use_idf=True,
    ).fit(train)
    clf = LogisticRegression(max_iter=40, learning_rate=0.3).fit(
        feats.transform(train)
    )

    scored = clf.transform(feats.transform(test))
    pred = np.asarray(scored["prediction"], np.float64)
    y = np.asarray(test["label"], np.float64)
    acc = float((pred == y).mean())
    print(f"holdout accuracy: {acc:.3f}")

    stats_in = scored.with_column(
        "scored_labels", pred, DataType.DOUBLE
    ).with_column(
        "scored_probabilities", np.asarray(scored["probability"]),
        DataType.VECTOR,
    )
    row = ComputeModelStatistics().transform(stats_in).collect()[0]
    print({k: round(float(v), 3) for k, v in row.items()
           if isinstance(v, (int, float))})
    assert acc > 0.9  # separable vocabulary: the pipeline must nail it
    print("OK")


if __name__ == "__main__":
    main()
