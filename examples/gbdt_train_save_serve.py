"""Example: train a GBDT classifier, save it in LightGBM text format,
reload it, and serve predictions over HTTP.

Run:  python examples/gbdt_train_save_serve.py
(On a machine with a TPU attached the fit runs there; otherwise set
JAX_PLATFORMS=cpu.)

The serving tier is the Spark Serving equivalent: the model becomes a web
service with continuous (per-request) scoring.
"""

import http.client
import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.core.dataframe import DataFrame, DataType
from mmlspark_tpu.gbdt import LightGBMClassificationModel, LightGBMClassifier
from mmlspark_tpu.serving import ServingServer, make_reply, parse_request


def main() -> None:
    # -- train ----------------------------------------------------------------
    rng = np.random.default_rng(0)
    n, d = 5000, 8
    y = rng.integers(0, 2, n).astype(np.float64)
    x = rng.normal(size=(n, d))
    x[:, 0] += 1.5 * y
    x[:, 1] -= 1.0 * y
    df = DataFrame.from_dict({"features": x, "label": y})

    clf = LightGBMClassifier(num_iterations=50, num_leaves=15)
    model = clf.fit(df)
    auc_probe = model.transform(df)["probability"][:, 1]
    print(f"trained: mean p(y=1 | y=1) = {auc_probe[y == 1].mean():.3f}, "
          f"p(y=1 | y=0) = {auc_probe[y == 0].mean():.3f}")

    # -- save / load (upstream LightGBM text format) -------------------------
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "model.txt")
        model.save_native_model(path)
        reloaded = LightGBMClassificationModel.load_native_model(path)
        print(f"saved + reloaded native model: {path}")

    # -- serve ---------------------------------------------------------------
    def handler(req_df):
        parsed = parse_request(req_df, {"features": DataType.VECTOR})
        scored = reloaded.transform(parsed)
        out = scored.with_column(
            "p1", scored["probability"][:, 1], DataType.DOUBLE
        )
        return make_reply(out, "p1")

    with ServingServer(handler, api_name="gbdt") as server:
        conn = http.client.HTTPConnection("127.0.0.1", server.port)
        for label in (0, 1):
            probe = x[y == label][0].tolist()
            body = json.dumps({"features": probe}).encode()
            conn.request("POST", "/gbdt", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            payload = resp.read()
            assert resp.status == 200, (resp.status, payload[:200])
            p1 = float(payload)
            print(f"served: true label {label} -> p(y=1) = {p1:.3f}")
        conn.close()
    print("OK")


if __name__ == "__main__":
    main()
