"""Example: distributed model serving — a worker pool behind a routing
gateway, micro-batch scoring, concurrent clients, and stage-latency
introspection.

Run:  python examples/distributed_serving.py
(Set JAX_PLATFORMS=cpu on machines without an accelerator.)

Mirrors the reference's Spark Serving deployment shape
(docs/mmlspark-serving.md: HTTP source -> pipeline -> HTTP sink), with the
worker pool standing in for executor-distributed endpoints.
"""

import http.client
import json
import os
import sys
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.core.dataframe import DataFrame, DataType
from mmlspark_tpu.gbdt import LightGBMClassifier
from mmlspark_tpu.serving import (
    DistributedServingServer,
    make_reply,
    parse_request,
)


def main() -> None:
    # -- train a model to serve ----------------------------------------------
    rng = np.random.default_rng(0)
    n, d = 3000, 6
    x = rng.normal(size=(n, d))
    y = ((x[:, 0] + 0.5 * x[:, 1] * x[:, 2]) > 0).astype(np.float64)
    model = LightGBMClassifier(num_iterations=30, num_leaves=15,
                               verbosity=0).fit(
        DataFrame.from_dict({"features": x, "label": y})
    )

    # -- handler: JSON {features: [...]} -> {probability} ---------------------
    def handler_factory():
        def handler(df):
            parsed = parse_request(df, {"features": DataType.VECTOR})
            scored = model.transform(parsed)
            prob = np.asarray(scored["probability"])[:, 1]
            return make_reply(
                scored.with_column("p", prob, DataType.DOUBLE), "p"
            )
        return handler

    # -- worker pool + gateway, micro-batch mode ------------------------------
    with DistributedServingServer(
        handler_factory, n_workers=2, api_name="score",
        mode="micro_batch", max_batch_size=32, max_wait_ms=5.0,
    ) as srv:
        print(f"serving at {srv.url} with {len(srv.workers)} workers")

        results, lock = [], threading.Lock()

        def client(rows):
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=30)
            for i in rows:
                body = json.dumps({"features": x[i].tolist()}).encode()
                conn.request("POST", "/score", body,
                             {"Content-Type": "application/json"})
                r = conn.getresponse()
                p = json.loads(r.read())
                with lock:
                    results.append((i, float(p)))
            conn.close()

        threads = [
            threading.Thread(target=client, args=(range(t, 80, 4),))
            for t in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        # served probabilities must match offline batch scoring exactly
        offline = model.transform(
            DataFrame.from_dict({"features": x[:80]})
        )["probability"][:, 1]
        for i, p in results:
            assert abs(p - offline[i]) < 1e-6

        # stage-latency decomposition (queue wait vs model run) per worker
        for w, worker in enumerate(srv.workers):
            print(f"worker {w} stages:", worker.stage_summary())

        # observability surfaces (docs/observability.md): scrape the
        # gateway exactly like Prometheus / a load balancer probe would
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        conn.request("GET", "/healthz")
        health = json.loads(conn.getresponse().read())
        conn.request("GET", "/metrics")
        metrics_text = conn.getresponse().read().decode()
        conn.close()
        assert health["status"] == "ok", health
        assert "serving_request_latency_ms" in metrics_text
        print(f"gateway healthz: {health['status']} "
              f"({len(health['workers'])} workers); /metrics "
              f"{len(metrics_text.splitlines())} lines")

    acc = float(((offline > 0.5) == y[:80]).mean())
    print(f"served 80 requests over 4 clients; agreement with offline "
          f"scoring exact; model train-acc on served rows {acc:.2f}")
    print("OK")


if __name__ == "__main__":
    main()
