"""Example: SAR recommender end-to-end — index string user/item ids, fit
Smart Adaptive Recommendations, score user-item pairs, and produce top-k
recommendations per user.

Run:  python examples/sar_recommender.py
(Set JAX_PLATFORMS=cpu on machines without an accelerator.)

Mirrors the reference's "SmartAdaptiveRecommendations" sample notebook flow
(RecommendationIndexer -> SAR -> recommendForAllUsers).
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.core.dataframe import DataFrame, DataType
from mmlspark_tpu.recommendation import SAR, RecommendationIndexer


def make_ratings(n=1500, n_users=100, n_items=60, seed=0):
    """Implicit-feedback triples with two taste clusters so similar items
    actually co-occur."""
    rng = np.random.default_rng(seed)
    users = rng.integers(0, n_users, n)
    taste = users % 2  # cluster 0 likes the first half of items
    half = n_items // 2
    items = np.where(
        rng.random(n) < 0.9,
        rng.integers(0, half, n) + taste * half,
        rng.integers(0, n_items, n),
    )
    return DataFrame.from_dict(
        {
            "customer": np.array([f"u{u:03d}" for u in users], object),
            "product": np.array([f"p{i:03d}" for i in items], object),
            "rating": rng.integers(1, 6, n).astype(np.float64),
        },
        types={"customer": DataType.STRING, "product": DataType.STRING},
    )


def main() -> None:
    ratings = make_ratings()

    # -- 1. string ids -> contiguous indices ----------------------------------
    indexer = RecommendationIndexer(
        user_input_col="customer", user_output_col="user_idx",
        item_input_col="product", item_output_col="item_idx",
    ).fit(ratings)
    indexed = indexer.transform(ratings)

    # -- 2. fit SAR (item-item similarity + user affinity) --------------------
    model = SAR(
        user_col="user_idx", item_col="item_idx", rating_col="rating",
        similarity_function="jaccard", support_threshold=2,
    ).fit(indexed)

    # -- 3. score the observed pairs ------------------------------------------
    scored = model.transform(indexed)
    assert np.isfinite(np.asarray(scored["prediction"], np.float64)).all()

    # -- 4. top-k recommendations for every user ------------------------------
    recs = model.recommend_for_all_users(num_items=5)
    first_user = int(recs["user_idx"][0])
    first_items = list(recs["recommendations"][0])
    print(f"user {first_user}: top-5 items {first_items}")
    assert len(first_items) == 5

    # cluster sanity: users in taste-cluster 0 should mostly be recommended
    # items from the first half of the catalog
    user_ids = np.asarray(recs["user_idx"], np.int64)
    labels = indexer.get(indexer.user_levels)
    hits = total = 0
    half_names = {f"p{i:03d}" for i in range(30)}
    item_levels = indexer.get(indexer.item_levels)
    for u, items in zip(user_ids, recs["recommendations"]):
        if int(labels[u][1:]) % 2 == 0:
            for it in items:
                hits += item_levels[int(it)] in half_names
                total += 1
    print(f"cluster-0 users recommended in-cluster items: {hits}/{total}")
    assert hits / total > 0.6
    print("OK")


if __name__ == "__main__":
    main()
