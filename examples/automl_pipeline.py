"""Example: the AutoML tier end-to-end — Featurize mixed columns, train
candidate models, tune hyperparameters with cross-validation, pick the best
model, and report metrics.

Run:  python examples/automl_pipeline.py
(Set JAX_PLATFORMS=cpu on machines without an accelerator.)

Mirrors the reference's model-training sample notebooks
(notebooks/samples "Classification - Adult Census" flow: TrainClassifier ->
TuneHyperparameters -> FindBestModel -> ComputeModelStatistics).
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.automl.find_best import FindBestModel
from mmlspark_tpu.automl.hyperparam import DefaultHyperparams, RandomSpace
from mmlspark_tpu.automl.statistics import ComputeModelStatistics
from mmlspark_tpu.automl.train import TrainClassifier
from mmlspark_tpu.automl.tune import TuneHyperparameters
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.featurize.assemble import Featurize
from mmlspark_tpu.gbdt import LightGBMClassifier
from mmlspark_tpu.ml import RandomForestClassifier


def make_census_like(n=1200, seed=0):
    """Adult-census-shaped table: numeric + string columns, binary label."""
    rng = np.random.default_rng(seed)
    age = rng.integers(18, 80, n).astype(np.float64)
    hours = np.clip(rng.normal(40, 10, n), 5, 90)
    edu = np.array(["hs", "college", "masters", "phd"], object)[
        rng.integers(0, 4, n)
    ]
    logit = 0.06 * (age - 40) + 0.05 * (hours - 40) + (edu == "phd") * 1.2 - 0.8
    label = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float64)
    return DataFrame.from_dict(
        {"age": age, "hours_per_week": hours, "education": edu, "label": label}
    )


def main() -> None:
    df = make_census_like()
    n_train = int(len(df) * 0.75)
    train = df.limit(n_train)
    test = df.filter(np.arange(len(df)) >= n_train)

    # -- 1. candidate models (TrainClassifier featurizes mixed columns) ------
    candidates = [
        TrainClassifier(model=LightGBMClassifier(num_iterations=40,
                                                 num_leaves=15),
                        label_col="label"),
        TrainClassifier(model=RandomForestClassifier(num_trees=25,
                                                     max_depth=5),
                        label_col="label"),
    ]

    # -- 2. hyperparameter tuning on the RF candidate -------------------------
    rf = RandomForestClassifier()
    space = RandomSpace(DefaultHyperparams.for_estimator(rf), seed=1)
    featurizer = Featurize(
        feature_columns=["age", "hours_per_week", "education"]
    ).fit(train)
    tuned = TuneHyperparameters(
        models=[rf], param_space=space, evaluation_metric="accuracy",
        number_of_folds=3, num_runs=4, parallelism=2, seed=0,
    ).fit(featurizer.transform(train))
    print("tuned best:", tuned.get_best_model_info())

    # -- 3. fit candidates, pick the best on held-out data --------------------
    fitted = [c.fit(train) for c in candidates]
    best = FindBestModel(models=fitted, evaluation_metric="AUC").fit(test)
    print("best model chosen; evaluating")

    # -- 4. metrics -----------------------------------------------------------
    scored = best.transform(test)
    stats = ComputeModelStatistics().transform(scored)
    row = stats.collect()[0]
    print({k: round(float(v), 4) for k, v in row.items()
           if isinstance(v, (int, float))})
    assert row["accuracy"] > 0.6
    print("OK")


if __name__ == "__main__":
    main()
