"""Benchmark entry point — run by the driver on real TPU hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extras"}.

Headline metric (BASELINE.json configs[1]): CIFAR10-shape ResNet-20 batch
inference through the full product path (DataFrame -> TPUModel.transform ->
scores column), i.e. the CNTKModel CIFAR10 notebook flow
(reference: CNTKModel.scala:469-516). Steady-state, compile excluded.

extras carries the other measured configs:
- gbdt_adult_fit_seconds / gbdt_adult_auc (BASELINE.json configs[0]):
  LightGBMClassifier.fit on an Adult-Census-shaped dataset (48842 rows,
  6 numeric + 8 categorical features, binary label), 100 iterations x 31
  leaves — the reference's headline LightGBM config. AUC on a 20% holdout.
- serving_p50_ms / serving_p99_ms: localhost continuous-mode serving
  latency (reference claim: "as low as 1 ms", docs/mmlspark-serving.md).

vs_baseline: the reference publishes no absolute numbers (SURVEY.md §6), so
the bar is BASELINE.md's north star — ">= 1x V100 wall-clock". We use a
nominal 6,000 imgs/sec for V100-era CNTK ResNet-20 batched eval (documented
estimate in BASELINE.md; the reference's own per-row JNI path was far below
this). vs_baseline = measured / 6000.

NOTE (BASELINE.md round 3): the chip is reached through a dev tunnel whose
host<->device bandwidth varies run to run (~20 MB/s to ~1.3 GB/s); the
CIFAR number moves with it. Transfers are serialized (concurrent in-flight
device_puts collapse tunnel throughput ~50x) and results are fetched once
(per-fetch D2H latency ~100 ms).
"""

import json
import sys
import time

import numpy as np

V100_CNTK_IMGS_PER_SEC = 6000.0  # documented estimate, see BASELINE.md
CPU_LIGHTGBM_ADULT_SECONDS = 3.0  # documented estimate, see BASELINE.md

N_IMAGES = 16384
BATCH = 8192
REPEATS = 5  # median-of-5 (round-3 verdict: best-of-3 hid tunnel variance)


def bench_cifar():
    """Returns (end_to_end imgs/sec, device_resident imgs/sec), both
    median-of-REPEATS. The split separates what the chip does from what the
    tunnel does, so a transfer regression can't masquerade as a compute one
    (round-3 verdict item 5; anti-pattern: CNTKModel.scala:71-140 per-row
    JNI eval)."""
    import jax

    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.dnn import resnet20_cifar
    from mmlspark_tpu.dnn.network import NetworkBundle
    from mmlspark_tpu.models import TPUModel

    rng = np.random.default_rng(0)
    # uint8 pixels, CIFAR layout: the realistic wire format (4x less
    # host->HBM traffic than f32; normalization happens on device)
    imgs = rng.integers(0, 256, size=(N_IMAGES, 32 * 32 * 3), dtype=np.uint8)
    df = DataFrame.from_dict({"images": imgs})

    net = resnet20_cifar(num_classes=10, compute_dtype="bfloat16")
    variables = net.init(jax.random.PRNGKey(0))
    model = TPUModel(
        NetworkBundle(net, variables),
        input_col="images",
        output_col="scores",
        mini_batch_size=BATCH,
    )

    model.transform(df.limit(BATCH))  # compile + warmup

    e2e = []
    for _ in range(REPEATS):
        t0 = time.time()
        out = model.transform(df)
        e2e.append(N_IMAGES / (time.time() - t0))
    assert out["scores"].shape == (N_IMAGES, 10)

    # device-resident: inputs pre-staged in HBM, outputs left on device —
    # pure (MXU compute + dispatch) throughput
    from mmlspark_tpu.models.tpu_model import _compiled_forward

    fn = _compiled_forward(net)
    x_dev = [
        jax.device_put(imgs[i : i + BATCH].reshape(-1, 32, 32, 3))
        for i in range(0, N_IMAGES, BATCH)
    ]
    jax.block_until_ready(fn(variables, x_dev[0]))  # warm
    resident = []
    for _ in range(REPEATS):
        t0 = time.time()
        ys = [fn(variables, xd) for xd in x_dev]
        jax.block_until_ready(ys)
        resident.append(N_IMAGES / (time.time() - t0))
    return float(np.median(e2e)), float(np.median(resident))


def make_adult_like(n: int = 48842, seed: int = 0):
    """Synthetic dataset with the Adult-Census schema: 6 numeric + 8
    categorical features, imbalanced binary label (~24% positive) with
    signal in both feature kinds."""
    rng = np.random.default_rng(seed)
    age = rng.integers(17, 90, n).astype(np.float64)
    fnlwgt = rng.lognormal(11.5, 0.7, n)
    education_num = rng.integers(1, 17, n).astype(np.float64)
    capital_gain = np.where(rng.random(n) < 0.08, rng.lognormal(8, 1.5, n), 0.0)
    capital_loss = np.where(rng.random(n) < 0.05, rng.lognormal(7, 0.8, n), 0.0)
    hours = np.clip(rng.normal(40, 12, n), 1, 99)
    cats = {
        "workclass": rng.integers(0, 9, n),
        "education": rng.integers(0, 16, n),
        "marital": rng.integers(0, 7, n),
        "occupation": rng.integers(0, 15, n),
        "relationship": rng.integers(0, 6, n),
        "race": rng.integers(0, 5, n),
        "sex": rng.integers(0, 2, n),
        "country": rng.integers(0, 42, n),
    }
    logit = (
        0.04 * (age - 38)
        + 0.25 * (education_num - 10)
        + 0.0004 * capital_gain
        + 0.02 * (hours - 40)
        + 0.35 * (cats["marital"] == 2)
        + 0.3 * (cats["occupation"] % 4 == 1)
        + 0.2 * cats["sex"]
        - 1.9
    )
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float64)
    x = np.column_stack(
        [age, fnlwgt, education_num, capital_gain, capital_loss, hours]
        + [cats[k].astype(np.float64) for k in cats]
    )
    cat_idx = list(range(6, 14))
    return x, y, cat_idx


def bench_gbdt():
    from mmlspark_tpu.core.dataframe import DataFrame, DataType
    from mmlspark_tpu.gbdt import LightGBMClassifier

    x, y, cat_idx = make_adult_like()
    n = len(y)
    holdout = np.zeros(n, bool)
    holdout[int(n * 0.8):] = True
    df = DataFrame.from_dict({"features": x[~holdout], "label": y[~holdout]})

    def fit_once():
        clf = LightGBMClassifier(
            num_iterations=100,
            num_leaves=31,
            max_bin=255,
            categorical_slot_indexes=cat_idx,
            verbosity=0,
        )
        return clf.fit(df)

    fit_once()  # compile warmup: jit kernels cache across fits
    t0 = time.time()
    model = fit_once()
    fit_seconds = time.time() - t0

    test = DataFrame.from_dict({"features": x[holdout]})
    p = model.transform(test)["probability"][:, 1]
    yt = y[holdout]
    order = np.argsort(p)
    ranks = np.empty(n - int(n * 0.8))
    ranks[order] = np.arange(1, len(p) + 1)
    pos = yt > 0
    auc = (ranks[pos].sum() - pos.sum() * (pos.sum() + 1) / 2) / (
        pos.sum() * (~pos).sum()
    )
    return fit_seconds, float(auc)


def bench_serving():
    import http.client

    from mmlspark_tpu.core.dataframe import DataType
    from mmlspark_tpu.serving import ServingServer, make_reply, parse_request

    def handler(df):
        parsed = parse_request(df)
        vals = np.asarray([float(v) for v in parsed["x"]])
        return make_reply(
            parsed.with_column("y", vals * 2.0, DataType.DOUBLE), "y"
        )

    with ServingServer(handler, api_name="bench") as server:
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=5)
        lat = []
        for i in range(500):
            body = json.dumps({"x": i}).encode()
            t0 = time.perf_counter()
            conn.request("POST", "/bench", body, {"Content-Type": "application/json"})
            r = conn.getresponse()
            r.read()
            lat.append(time.perf_counter() - t0)
        conn.close()
    lat = sorted(lat[50:])
    return lat[len(lat) // 2] * 1000, lat[int(len(lat) * 0.99)] * 1000


def bench_distributed_serving():
    """Concurrent serving through the worker-pool gateway: 8 keep-alive
    clients. Two paths, reported separately (round-3 verdict item 6):
    - trivial handler (x -> 2x): protocol + routing floor
    - ResNet-20 model path (batch-1 jit eval per request): the honest
      model-in-the-loop number on this chip
    """
    import http.client
    import threading

    import jax

    from mmlspark_tpu.core.dataframe import DataFrame, DataType
    from mmlspark_tpu.dnn import resnet20_cifar
    from mmlspark_tpu.dnn.network import NetworkBundle
    from mmlspark_tpu.models import TPUModel
    from mmlspark_tpu.serving import (
        DistributedServingServer,
        make_reply,
        parse_request,
    )

    def run_load(srv, api, payload, n_clients=8, n_requests=40, warmup=4):
        for _ in range(warmup):
            conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=60)
            body = json.dumps(payload).encode()
            conn.request("POST", f"/{api}", body,
                         {"Content-Type": "application/json"})
            conn.getresponse().read()
            conn.close()
        lat, errors, lock = [], [], threading.Lock()

        def client():
            try:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", srv.port, timeout=60
                )
                body = json.dumps(payload).encode()
                for _ in range(n_requests):
                    t0 = time.perf_counter()
                    conn.request("POST", f"/{api}", body,
                                 {"Content-Type": "application/json"})
                    r = conn.getresponse()
                    r.read()
                    dt = time.perf_counter() - t0
                    with lock:
                        if r.status != 200:
                            errors.append(r.status)
                        else:
                            lat.append(dt)
                conn.close()
            except Exception as e:  # surface, don't die silently
                with lock:
                    errors.append(repr(e))

        threads = [threading.Thread(target=client) for _ in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors or not lat:
            raise RuntimeError(f"serving load errors: {errors[:5]}")
        lat = sorted(lat)
        return lat[len(lat) // 2] * 1000, lat[int(len(lat) * 0.99)] * 1000

    # trivial path
    def trivial_factory():
        def handler(df):
            parsed = parse_request(df)
            vals = np.asarray([float(v) for v in parsed["x"]])
            return make_reply(
                parsed.with_column("y", vals * 2.0, DataType.DOUBLE), "y"
            )
        return handler

    with DistributedServingServer(
        trivial_factory, n_workers=4, api_name="bench"
    ) as srv:
        triv_p50, triv_p99 = run_load(srv, "bench", {"x": 1.0})

    # model path: ResNet-20 in MICRO-BATCH mode — concurrent requests share
    # one jit dispatch (the deployment shape for model serving; batch-1
    # continuous dispatch pays full tunnel latency per request)
    net = resnet20_cifar(num_classes=10, compute_dtype="bfloat16")
    variables = net.init(jax.random.PRNGKey(0))
    bundle = NetworkBundle(net, variables)

    def model_factory():
        model = TPUModel(bundle, input_col="img", output_col="scores",
                         mini_batch_size=8)

        def handler(df):
            parsed = parse_request(df, {"img": DataType.VECTOR})
            scored = model.transform(parsed)
            out = scored.with_column(
                "top", np.argmax(scored["scores"], axis=1).astype(np.float64),
                DataType.DOUBLE,
            )
            return make_reply(out, "top")

        return handler

    img = np.zeros(32 * 32 * 3, np.float32).tolist()
    with DistributedServingServer(
        model_factory, n_workers=1, api_name="model",
        mode="micro_batch", max_batch_size=8, max_wait_ms=10.0,
    ) as srv:
        model_p50, model_p99 = run_load(
            srv, "model", {"img": img}, n_requests=15
        )
    return triv_p50, triv_p99, model_p50, model_p99


def main() -> int:
    imgs_per_sec, imgs_per_sec_resident = bench_cifar()
    gbdt_seconds, gbdt_auc = bench_gbdt()
    p50, p99 = bench_serving()
    d_p50, d_p99, m_p50, m_p99 = bench_distributed_serving()

    print(
        json.dumps(
            {
                "metric": "cifar10_resnet20_inference",
                "value": round(imgs_per_sec, 1),
                "unit": "imgs/sec/chip",
                "vs_baseline": round(imgs_per_sec / V100_CNTK_IMGS_PER_SEC, 3),
                "extras": {
                    "cifar_device_resident_imgs_per_sec": round(
                        imgs_per_sec_resident, 1
                    ),
                    "gbdt_adult_fit_seconds": round(gbdt_seconds, 2),
                    "gbdt_adult_fit_vs_cpu_baseline": round(
                        CPU_LIGHTGBM_ADULT_SECONDS / gbdt_seconds, 3
                    ),
                    "gbdt_adult_auc": round(gbdt_auc, 4),
                    "serving_p50_ms": round(p50, 3),
                    "serving_p99_ms": round(p99, 3),
                    "serving_pool8_p50_ms": round(d_p50, 3),
                    "serving_pool8_p99_ms": round(d_p99, 3),
                    "serving_resnet20_p50_ms": round(m_p50, 3),
                    "serving_resnet20_p99_ms": round(m_p99, 3),
                },
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
