"""Benchmark entry point — run by the driver on real TPU hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extras"}.

`python bench.py --smoke` instead runs the CPU-safe dataplane smoke bench
(tiny shapes; also wired into tier-1 via tests/test_bench_smoke.py): it
measures stage-boundary transfer/compile counts for the device-resident
columnar dataplane against the pre-change host-round-trip dataflow and
writes BENCH_pr03.json. See run_smoke and docs/dataplane.md.

Headline metric (BASELINE.json configs[1]): CIFAR10-shape ResNet-20 batch
inference through the full product path (DataFrame -> TPUModel.transform ->
scores column), i.e. the CNTKModel CIFAR10 notebook flow
(reference: CNTKModel.scala:469-516). Steady-state, compile excluded.

extras carries the other measured configs:
- gbdt_adult_fit_seconds / gbdt_adult_auc (BASELINE.json configs[0]):
  LightGBMClassifier.fit on an Adult-Census-shaped dataset (48842 rows,
  6 numeric + 8 categorical features, binary label), 100 iterations x 31
  leaves — the reference's headline LightGBM config. AUC on a 20% holdout.
- serving_p50_ms / serving_p99_ms: localhost continuous-mode serving
  latency (reference claim: "as low as 1 ms", docs/mmlspark-serving.md).

vs_baseline: the reference publishes no absolute numbers (SURVEY.md §6), so
the bar is BASELINE.md's north star — ">= 1x V100 wall-clock". We use a
nominal 6,000 imgs/sec for V100-era CNTK ResNet-20 batched eval (documented
estimate in BASELINE.md; the reference's own per-row JNI path was far below
this). vs_baseline = measured / 6000.

NOTE (BASELINE.md round 3): the chip is reached through a dev tunnel whose
host<->device bandwidth varies run to run (~20 MB/s to ~1.3 GB/s); the
CIFAR number moves with it. Transfers are serialized (concurrent in-flight
device_puts collapse tunnel throughput ~50x) and results are fetched once
(per-fetch D2H latency ~100 ms).
"""

import contextlib
import json
import sys
import time

import numpy as np

V100_CNTK_IMGS_PER_SEC = 6000.0  # documented estimate, see BASELINE.md

N_IMAGES = 16384
BATCH = 8192
REPEATS = 5  # median-of-5 (round-3 verdict: best-of-3 hid tunnel variance)

# -- artifact provenance + clobber guard ---------------------------------------
# Every BENCH_*.json writer stamps a provenance block (which sha, which box,
# how loaded) and refuses to overwrite a previously-PASSING committed
# artifact with a round that fails that bench's own tier-1 gates — the
# PR 8/9/13 noisy-round incident class (a casual re-run on a loaded box
# clobbering the artifact of record with a failing measurement), fixed by
# hand three times and now structural. `python bench.py --smoke --force`
# is the escape hatch for intentionally recording a failing round.

_FORCE_WRITE = False


def _provenance() -> dict:
    """Where this artifact came from: git sha, host load, core count, UTC
    timestamp — enough to spot 'recorded on a loaded box' in review."""
    import datetime
    import os
    import subprocess

    try:
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        ).stdout.strip() or "unknown"
    except (OSError, subprocess.SubprocessError):
        sha = "unknown"
    try:
        loadavg = [round(x, 2) for x in os.getloadavg()]
    except OSError:
        loadavg = [-1.0, -1.0, -1.0]
    return {
        "git_sha": sha,
        "loadavg": loadavg,
        "cpu_count": os.cpu_count(),
        "utc": datetime.datetime.now(
            datetime.timezone.utc
        ).isoformat(timespec="seconds"),
    }


def _gate_ok(gate, report: dict) -> bool:
    """Does `report` pass its bench's own tier-1 gates? Structural damage
    (missing keys from an older schema) counts as failing."""
    try:
        return bool(gate(report))
    except (KeyError, TypeError, IndexError, ValueError):
        return False


def _write_report(report: dict, out_path: str) -> dict:
    """Stamp provenance and write `out_path` — unless that would clobber
    an existing PASSING artifact with a round that fails its own gates
    (the guard; --force overrides). Always returns the stamped report, so
    callers gate on the round they measured either way."""
    import os

    report = dict(report)
    report["provenance"] = _provenance()
    if not out_path:
        return report
    gate = _BENCH_GATES.get(os.path.basename(out_path))
    if gate is not None and not _FORCE_WRITE and os.path.exists(out_path):
        if not _gate_ok(gate, report):
            try:
                with open(out_path) as f:
                    old_ok = _gate_ok(gate, json.load(f))
            except (OSError, ValueError):
                old_ok = False
            if old_ok:
                print(json.dumps({
                    "bench_clobber_guard": os.path.basename(out_path),
                    "action": "kept existing passing artifact",
                    "reason": "this round fails the bench's own tier-1 "
                              "gates (noisy box?); re-run quiet or pass "
                              "--force",
                }, sort_keys=True), file=sys.stderr)
                return report
    # tmp + os.replace: a crash mid-write must not destroy the artifact of
    # record (the same discipline graftcheck's non-atomic-artifact-write
    # rule enforces in the persistence tier)
    tmp_path = out_path + ".tmp"
    with open(tmp_path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp_path, out_path)
    return report


def _gate_pr03(r):
    chain = r["tpu_model_chain"]
    res, base = chain["resident"], chain["baseline_host_roundtrip"]
    srv = r["serving_ragged"]
    b, fx = srv["bucketed_resident"], srv["baseline_fixed_pad_roundtrip"]
    return (
        res["h2d_transfers"] < base["h2d_transfers"]
        and res["d2h_transfers"] < base["d2h_transfers"]
        and res["h2d_bytes"] < base["h2d_bytes"]
        and 0 < srv["max_programs_per_stage"] <= 8
        and b["h2d_transfers"] < fx["h2d_transfers"]
        and b["d2h_transfers"] < fx["d2h_transfers"]
        and b["h2d_bytes"] < fx["h2d_bytes"]
    )


def _gate_pr04(r):
    e = r["serving_engines"]
    return (
        e["throughput_speedup"] >= 1.3
        and e["pipelined"]["p99_ms"] <= e["sync"]["p99_ms"]
    )


def _gate_pr05(r):
    o = r["obs_overhead"]
    return o["overhead_frac"] <= 0.05 and o["trace"]["full_span_trees"] > 0


def _gate_pr06(r):
    ft = r["fault_tolerance"]
    kill, wedge = ft["kill_1_of_4"], ft["wedge_breaker"]
    shed, swap = ft["overload_shed"], ft["replace_under_load"]
    return (
        kill["error_rate"] < 0.01
        and kill["recovery_ms"] is not None
        and kill["recovery_ms"] < 500.0
        and kill["p99_ms"] < 1000.0
        and wedge["breaker_tripped"]
        and wedge["error_rate"] < 0.01
        and wedge["p99_ms"] < 1500.0
        and shed["shed_429"] > 0
        and shed["p99_ratio_vs_baseline"] is not None
        and shed["p99_ratio_vs_baseline"] <= 2.0
        and swap["errors"] == 0
    )


def _gate_pr07(r):
    pf = r["prefetch"]
    return (
        r["fused_prep"]["speedup"] >= 2.5
        and r["featurize_e2e"]["speedup"] >= 1.5
        and pf["uploads_overlapping_prev_compute"]
        >= (pf["batches"] - 1) // 2
        and pf["overlap_ratio"] >= 0.5
        and pf["speedup"] >= 0.8
        and r["bf16"]["top1_match"]
        and r["bf16"]["rel_logit_mae"] < r["bf16"]["tolerance"]
    )


def _gate_pr08(r):
    return (
        r["learner_recovery"]["killed_mid_fit"]
        and r["learner_recovery"]["resume_parity_delta"] == 0.0
        and r["gbdt_recovery"]["resume_parity_delta"] == 0.0
        and all(row["green"] for row in r["fault_matrix"].values())
        and r["checkpoint_overhead"]["learner_overhead_frac"] <= 0.05
        and r["checkpoint_overhead"]["gbdt_overhead_frac"] <= 0.05
        and r["learner_recovery"]["recovery_ms"] < 1000.0
    )


def _gate_pr09(r):
    return (
        r["parity"]["determinism_delta"] == 0.0
        and r["parity"]["max_raw_delta"] <= 1e-3
        and r["footprint"]["peak_ratio"] <= 0.5
        and r["transfers"]["uploads_per_visit"]
        == float(r["transfers"]["payload_leaves"])
        and not r["transfers"]["per_row_h2d"]
        and r["checkpoint_compose"]["resume_identical"]
        and r["wall_clock"]["ratio"] <= 1.3
        and r["prefetch"]["overlap_ratio"] >= 0.8
    )


def _gate_pr13(r):
    lo, hi = r["mfu"]["tolerance_band"]
    fl = r["profiler_overhead"]["instrumented"]["flight"]
    return (
        r["profiler_overhead"]["overhead_frac"] <= 0.05
        and lo <= r["mfu"]["ratio_runtime_vs_analytic"] <= hi
        and fl["schema_complete"]
        and fl["window_dispatches"] == fl["window_dispatch_counter"]
    )


def _gate_pr15(r):
    t, p, s = r["throughput"], r["parity"], r["streamed_sharded"]
    return (
        t["ratio_vs_fused"] >= 4.0
        and p["trees_bit_identical"]
        and p["determinism_delta"] == 0.0
        and s["peak_ratio"] <= 0.5
        and s["uploads_per_visit"] == float(s["payload_leaves"])
        and not s["per_row_h2d"]
        and r["transfers_dp"]["resident_uploads"]
        == r["transfers_dp"]["expected_resident_uploads"]
        and not r["transfers_dp"]["per_row_h2d"]
        and r["checkpoint_compose"]["killed_mid_fit"]
        and r["checkpoint_compose"]["resume_identical"]
    )


def _gate_pr14(r):
    t, s = r["trace_propagation"], r["slo"]
    return (
        t["cross_process_tree"]
        and t["attempt_children"] >= 2
        and s["fast_alert_fired"]
        and s["healthz_degraded"]
        and s["worker_healthz_degraded"]
        and not s["control_alerted"]
        and s["healthz_recovered_ok"]
        and r["overhead"]["overhead_frac"] <= 0.05
    )


def _gate_pr16(r):
    m = r["memory"]
    c, rec, leak = m["cycle"], m["reconcile"], m["leak"]
    skew, ov = m["skew"], m["overhead"]
    return (
        c["returned_to_baseline"]
        and c["model_weights_bytes"] > 0
        and c["dispatch_programs_bytes"] > 0
        and c["prefetch_chunks_mid_bytes"] > 0
        and c["prefetch_chunks_end_bytes"] == 0
        and rec["drifted"] == []
        and rec["devices_checked"] > 0
        and leak["detected"]
        and leak["class"] == "scratch"
        and skew["balanced_ratio"] is not None
        and skew["balanced_ratio"] <= 2.0
        and skew["straggler"]["ratio"] is not None
        and skew["straggler"]["ratio"] >= skew["factor"]
        and skew["straggler"]["warnings_fired"] >= 1
        and ov["overhead_frac"] <= 0.05
    )


def _gate_pr18(r):
    d = r["dnn_training"]
    p, ov, up = d["pipeline"], d["overlap"], d["uploads"]
    return (
        p["speedup_vs_legacy"] >= 1.3
        and p["loss_delta_pipelined_vs_depth0"] == 0.0
        and ov["overlap_ratio"] >= 0.8
        and up["exact"]
        and up["h2d_transfers"] == up["expected_transfers"]
        and d["mfu"]["device_mfu"] is not None
        and d["mfu"]["device_mfu"] > 0.0
        and d["accumulation"]["rerun_delta"] == 0.0
        and d["out_of_core"]["peak_ratio"] <= 0.6
        and d["recovery"]["crash_injected"]
        and d["recovery"]["resume_delta"] == 0.0
    )


def _gate_pr19(r):
    ip = r["interpret_parity"]
    i8 = r["int8"]
    mfu = r["mfu_attribution"]
    return (
        all(ip["trees_bit_identical"].values())
        and ip["split_finder"]["decisions_identical"]
        # f32-ulp accumulation band (prefix-matmul vs sequential cumsum);
        # near-zero gains inflate the relative measure, so the bound is
        # loose vs the observed ~1e-5 — a real kernel bug (wrong prefix,
        # lost regularizer) moves gains by orders of magnitude, not ulps
        and ip["split_finder"]["gain_max_rel_delta"] <= 1e-4
        and ip["scoring"]["bitwise_identical"]
        and ip["int8_matmul_max_abs_delta"] <= 1e-4
        and i8["mlp"]["rel_logit_mae"] <= i8["tolerance"]
        and i8["mlp"]["top1_exact"]
        and i8["conv"]["rel_logit_mae"] <= i8["tolerance"]
        and i8["conv"]["top1_exact"]
        and mfu["pallas_rows"] >= 1
        and mfu["einsum_rows"] >= 1
    )


def _gate_pr20(r):
    f = r["federation"]
    rec, slo = f["reconciliation"], f["cluster_slo"]
    mem, kill, ov = f["memory_scope"], f["kill"], f["overhead"]
    return (
        rec["exact"]
        and rec["completed_requests"] > 0
        and slo["burst_500s"] >= 8
        and slo["alert_fired"]
        and slo["healthz_degraded"]
        and slo["cluster_slos_served"]
        and mem["zero_drift"]
        and mem["errors"] == 0
        and len(mem["procs"]) >= 1
        and kill["partial_errors"] >= 1
        and kill["procs_still_served"] >= 1
        and kill["scrape_failures_total"] >= 1
        and kill["staleness_rising"]
        and kill["scrape_stale_flagged"]
        and ov["overhead_frac"] <= 0.05
    )


#: artifact basename -> that bench's own tier-1 gate (the clobber guard)
_BENCH_GATES = {
    "BENCH_pr03.json": _gate_pr03,
    "BENCH_pr04.json": _gate_pr04,
    "BENCH_pr05.json": _gate_pr05,
    "BENCH_pr06.json": _gate_pr06,
    "BENCH_pr07.json": _gate_pr07,
    "BENCH_pr08.json": _gate_pr08,
    "BENCH_pr09.json": _gate_pr09,
    "BENCH_pr13.json": _gate_pr13,
    "BENCH_pr14.json": _gate_pr14,
    "BENCH_pr15.json": _gate_pr15,
    "BENCH_pr16.json": _gate_pr16,
    "BENCH_pr18.json": _gate_pr18,
    "BENCH_pr19.json": _gate_pr19,
    "BENCH_pr20.json": _gate_pr20,
}

def peak_flops() -> float:
    """Best-effort bf16 peak for the attached chip; 0 when unknown (MFU
    lines are then omitted rather than wrong). The table itself lives in
    core/env.py now — the runtime profiler's device_mfu gauges divide by
    the same constants, so bench MFU and /metrics MFU agree by
    construction. Unlike env.peak_flops_per_sec, this returns 0 for the
    CPU backend: the driver bench reports MFU only on real chips."""
    import jax

    from mmlspark_tpu.core.env import peak_flops_per_sec

    if jax.default_backend() == "cpu":
        return 0.0
    return peak_flops_per_sec()


def mfu(imgs_per_sec: float, flops_per_img: float) -> float:
    """Model FLOPs utilization in percent, or -1 when peak is unknown."""
    peak = peak_flops()
    if peak <= 0:
        return -1.0
    return round(100.0 * imgs_per_sec * flops_per_img / peak, 2)


def bench_cifar():
    """Returns (end_to_end imgs/sec, device_resident imgs/sec), both
    median-of-REPEATS. The split separates what the chip does from what the
    tunnel does, so a transfer regression can't masquerade as a compute one
    (round-3 verdict item 5; anti-pattern: CNTKModel.scala:71-140 per-row
    JNI eval)."""
    import jax

    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.dnn import resnet20_cifar
    from mmlspark_tpu.dnn.network import NetworkBundle
    from mmlspark_tpu.models import TPUModel

    rng = np.random.default_rng(0)
    # uint8 pixels, CIFAR layout: the realistic wire format (4x less
    # host->HBM traffic than f32; normalization happens on device)
    imgs = rng.integers(0, 256, size=(N_IMAGES, 32 * 32 * 3), dtype=np.uint8)
    df = DataFrame.from_dict({"images": imgs})

    net = resnet20_cifar(num_classes=10, compute_dtype="bfloat16")
    variables = net.init(jax.random.PRNGKey(0))
    model = TPUModel(
        NetworkBundle(net, variables),
        input_col="images",
        output_col="scores",
        mini_batch_size=BATCH,
    )

    model.transform(df.limit(BATCH))  # compile + warmup

    e2e = []
    for _ in range(REPEATS):
        t0 = time.time()
        out = model.transform(df)
        e2e.append(N_IMAGES / (time.time() - t0))
    assert out["scores"].shape == (N_IMAGES, 10)

    # device-resident: inputs pre-staged in HBM, outputs left on device —
    # pure (MXU compute + dispatch) throughput
    from mmlspark_tpu.models.tpu_model import _compiled_forward

    fn = _compiled_forward(net)
    x_dev = [
        jax.device_put(imgs[i : i + BATCH].reshape(-1, 32, 32, 3))
        for i in range(0, N_IMAGES, BATCH)
    ]
    jax.block_until_ready(fn(variables, x_dev[0]))  # warm
    resident = []
    for _ in range(REPEATS):
        t0 = time.time()
        ys = [fn(variables, xd) for xd in x_dev]
        jax.block_until_ready(ys)
        resident.append(N_IMAGES / (time.time() - t0))
    return float(np.median(e2e)), float(np.median(resident))


def make_adult_like(n: int = 48842, seed: int = 0):
    """Synthetic dataset with the Adult-Census schema: 6 numeric + 8
    categorical features, imbalanced binary label (~24% positive) with
    signal in both feature kinds."""
    rng = np.random.default_rng(seed)
    age = rng.integers(17, 90, n).astype(np.float64)
    fnlwgt = rng.lognormal(11.5, 0.7, n)
    education_num = rng.integers(1, 17, n).astype(np.float64)
    capital_gain = np.where(rng.random(n) < 0.08, rng.lognormal(8, 1.5, n), 0.0)
    capital_loss = np.where(rng.random(n) < 0.05, rng.lognormal(7, 0.8, n), 0.0)
    hours = np.clip(rng.normal(40, 12, n), 1, 99)
    cats = {
        "workclass": rng.integers(0, 9, n),
        "education": rng.integers(0, 16, n),
        "marital": rng.integers(0, 7, n),
        "occupation": rng.integers(0, 15, n),
        "relationship": rng.integers(0, 6, n),
        "race": rng.integers(0, 5, n),
        "sex": rng.integers(0, 2, n),
        "country": rng.integers(0, 42, n),
    }
    logit = (
        0.04 * (age - 38)
        + 0.25 * (education_num - 10)
        + 0.0004 * capital_gain
        + 0.02 * (hours - 40)
        + 0.35 * (cats["marital"] == 2)
        + 0.3 * (cats["occupation"] % 4 == 1)
        + 0.2 * cats["sex"]
        - 1.9
    )
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float64)
    x = np.column_stack(
        [age, fnlwgt, education_num, capital_gain, capital_loss, hours]
        + [cats[k].astype(np.float64) for k in cats]
    )
    cat_idx = list(range(6, 14))
    return x, y, cat_idx


def _auc(p: np.ndarray, yt: np.ndarray) -> float:
    order = np.argsort(p)
    ranks = np.empty(len(p))
    ranks[order] = np.arange(1, len(p) + 1)
    pos = yt > 0
    return float(
        (ranks[pos].sum() - pos.sum() * (pos.sum() + 1) / 2)
        / (pos.sum() * (~pos).sum())
    )


def _sklearn_gbdt_bar(x_train, y_train, x_test, y_test, cat_idx):
    """MEASURED CPU bar (round-4 verdict item 1: the 3.0s constant was a
    guess nobody timed): sklearn HistGradientBoostingClassifier — the same
    histogram-GBDT family — fit on the identical train matrix, timed in
    this very run on this very machine."""
    from sklearn.ensemble import HistGradientBoostingClassifier

    cat_mask = np.zeros(x_train.shape[1], bool)
    cat_mask[list(cat_idx)] = True
    clf = HistGradientBoostingClassifier(
        max_iter=100, max_leaf_nodes=31, max_bins=255,
        categorical_features=cat_mask,
        early_stopping=False,
    )
    t0 = time.time()
    clf.fit(x_train, y_train)
    fit_seconds = time.time() - t0
    auc = _auc(clf.predict_proba(x_test)[:, 1], y_test)
    return fit_seconds, auc


def make_higgs_like(n: int = 1_000_000, f: int = 30, seed: int = 0):
    """1M x 30 synthetic binary task (6 integer-coded categoricals) — the
    at-scale GBDT config (reference speed pitch is Higgs-scale,
    docs/lightgbm.md:17-21; round-4 verdict item 1 asked for >=1M rows)."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, f))
    for j in range(f - 6, f):
        x[:, j] = rng.integers(0, 20, n)
    logit = (
        0.8 * x[:, 0] - 0.5 * x[:, 1] + 0.3 * x[:, 2] * x[:, 3]
        + 0.4 * (x[:, f - 1] % 4 == 1) - 0.2
    )
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float64)
    cat_idx = list(range(f - 6, f))
    return x, y, cat_idx


def _bench_gbdt_config(x, y, cat_idx, train_frac: float = 0.8):
    """Fit ours + the measured sklearn bar on one dataset; returns a dict of
    fit seconds / speedup / AUCs."""
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.gbdt import LightGBMClassifier

    n = len(y)
    holdout = np.zeros(n, bool)
    holdout[int(n * train_frac):] = True
    df = DataFrame.from_dict({"features": x[~holdout], "label": y[~holdout]})

    def fit_once():
        clf = LightGBMClassifier(
            num_iterations=100,
            num_leaves=31,
            max_bin=255,
            categorical_slot_indexes=cat_idx,
            verbosity=0,
        )
        return clf.fit(df)

    fit_once()  # compile warmup: jit kernels cache across fits
    t0 = time.time()
    model = fit_once()
    fit_seconds = time.time() - t0

    test = DataFrame.from_dict({"features": x[holdout]})
    p = model.transform(test)["probability"][:, 1]
    auc = _auc(p, y[holdout])

    cpu_seconds, cpu_auc = _sklearn_gbdt_bar(
        x[~holdout], y[~holdout], x[holdout], y[holdout], cat_idx
    )
    return {
        "fit_seconds": round(fit_seconds, 2),
        "cpu_sklearn_seconds": round(cpu_seconds, 2),
        "fit_vs_measured_cpu": round(cpu_seconds / fit_seconds, 3),
        "auc": round(auc, 4),
        "cpu_auc": round(cpu_auc, 4),
    }


def bench_gbdt():
    x, y, cat_idx = make_adult_like()
    return _bench_gbdt_config(x, y, cat_idx)


def bench_gbdt_1m():
    x, y, cat_idx = make_higgs_like()
    return _bench_gbdt_config(x, y, cat_idx)


def bench_resnet50():
    """ResNet-50 (zoo flagship, ~25.5M params, 8.2 GFLOPs/img) featurization
    throughput through TPUModel, truncated at the 2048-d pool layer — the
    transfer-learning path the reference drives with downloadByName
    ("ResNet50") (ModelDownloader.scala:209-267). Returns (e2e imgs/sec,
    device-resident imgs/sec, flops_per_img). Device-resident feeds the MFU
    line: at this model size the chip should actually be working."""
    import jax

    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.dnn.zoo_builders import resnet50_random
    from mmlspark_tpu.dnn.network import NetworkBundle
    from mmlspark_tpu.models import TPUModel

    n_images, batch = 1024, 128
    bundle = resnet50_random()  # deterministic rebuild, no 100MB blob in-repo
    net = bundle.network.truncate_at("pool")
    net.compute_dtype = "bfloat16"
    headless = NetworkBundle(net, bundle.variables)
    flops_per_img = net.flops_per_example()

    rng = np.random.default_rng(0)
    imgs = rng.integers(
        0, 256, size=(n_images, 224 * 224 * 3), dtype=np.uint8
    )
    df = DataFrame.from_dict({"images": imgs})
    model = TPUModel(headless, input_col="images", output_col="features",
                     mini_batch_size=batch)
    model.transform(df.limit(batch))  # compile + warmup

    e2e = []
    for _ in range(3):
        t0 = time.time()
        out = model.transform(df)
        e2e.append(n_images / (time.time() - t0))
    assert out["features"].shape == (n_images, 2048)

    from mmlspark_tpu.models.tpu_model import _compiled_forward

    fn = _compiled_forward(net)
    variables = headless.device_variables()
    x_dev = [
        jax.device_put(imgs[i: i + batch].reshape(-1, 224, 224, 3))
        for i in range(0, n_images, batch)
    ]
    jax.block_until_ready(fn(variables, x_dev[0]))  # warm
    resident = []
    for _ in range(REPEATS):
        t0 = time.time()
        ys = [fn(variables, xd) for xd in x_dev]
        jax.block_until_ready(ys)
        resident.append(n_images / (time.time() - t0))
    return float(np.median(e2e)), float(np.median(resident)), flops_per_img


def bench_serving():
    import http.client

    from mmlspark_tpu.core.dataframe import DataType
    from mmlspark_tpu.serving import ServingServer, make_reply, parse_request

    def handler(df):
        parsed = parse_request(df)
        vals = np.asarray([float(v) for v in parsed["x"]])
        return make_reply(
            parsed.with_column("y", vals * 2.0, DataType.DOUBLE), "y"
        )

    with ServingServer(handler, api_name="bench") as server:
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=5)
        lat = []
        for i in range(500):
            body = json.dumps({"x": i}).encode()
            t0 = time.perf_counter()
            conn.request("POST", "/bench", body, {"Content-Type": "application/json"})
            r = conn.getresponse()
            r.read()
            lat.append(time.perf_counter() - t0)
        conn.close()
    lat = sorted(lat[50:])
    return lat[len(lat) // 2] * 1000, lat[int(len(lat) * 0.99)] * 1000


def bench_distributed_serving():
    """Concurrent serving through the worker-pool gateway: 8 keep-alive
    clients. Two paths, reported separately (round-3 verdict item 6):
    - trivial handler (x -> 2x): protocol + routing floor
    - ResNet-20 model path (batch-1 jit eval per request): the honest
      model-in-the-loop number on this chip
    """
    import http.client
    import threading

    import jax

    from mmlspark_tpu.core.dataframe import DataFrame, DataType
    from mmlspark_tpu.dnn import resnet20_cifar
    from mmlspark_tpu.dnn.network import NetworkBundle
    from mmlspark_tpu.models import TPUModel
    from mmlspark_tpu.serving import (
        DistributedServingServer,
        make_reply,
        parse_request,
    )

    def run_load(srv, api, payload, n_clients=8, n_requests=40, warmup=4):
        for _ in range(warmup):
            conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=60)
            body = json.dumps(payload).encode()
            conn.request("POST", f"/{api}", body,
                         {"Content-Type": "application/json"})
            conn.getresponse().read()
            conn.close()
        lat, errors, lock = [], [], threading.Lock()

        def client():
            try:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", srv.port, timeout=60
                )
                body = json.dumps(payload).encode()
                for _ in range(n_requests):
                    t0 = time.perf_counter()
                    conn.request("POST", f"/{api}", body,
                                 {"Content-Type": "application/json"})
                    r = conn.getresponse()
                    r.read()
                    dt = time.perf_counter() - t0
                    with lock:
                        if r.status != 200:
                            errors.append(r.status)
                        else:
                            lat.append(dt)
                conn.close()
            except Exception as e:  # surface, don't die silently
                with lock:
                    errors.append(repr(e))

        threads = [threading.Thread(target=client) for _ in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors or not lat:
            raise RuntimeError(f"serving load errors: {errors[:5]}")
        lat = sorted(lat)
        return lat[len(lat) // 2] * 1000, lat[int(len(lat) * 0.99)] * 1000

    # trivial path
    def trivial_factory():
        def handler(df):
            parsed = parse_request(df)
            vals = np.asarray([float(v) for v in parsed["x"]])
            return make_reply(
                parsed.with_column("y", vals * 2.0, DataType.DOUBLE), "y"
            )
        return handler

    with DistributedServingServer(
        trivial_factory, n_workers=4, api_name="bench"
    ) as srv:
        triv_p50, triv_p99 = run_load(srv, "bench", {"x": 1.0})

    # model path: ResNet-20 in MICRO-BATCH mode — concurrent requests share
    # one jit dispatch (the deployment shape for model serving; batch-1
    # continuous dispatch pays full tunnel latency per request)
    net = resnet20_cifar(num_classes=10, compute_dtype="bfloat16")
    variables = net.init(jax.random.PRNGKey(0))
    bundle = NetworkBundle(net, variables)

    def model_factory():
        model = TPUModel(bundle, input_col="img", output_col="scores",
                         mini_batch_size=8)

        def handler(df):
            parsed = parse_request(df, {"img": DataType.VECTOR})
            scored = model.transform(parsed)
            out = scored.with_column(
                "top", np.argmax(scored["scores"], axis=1).astype(np.float64),
                DataType.DOUBLE,
            )
            return make_reply(out, "top")

        return handler

    img = np.zeros(32 * 32 * 3, np.float32).tolist()
    with DistributedServingServer(
        model_factory, n_workers=1, api_name="model",
        mode="micro_batch", max_batch_size=8, max_wait_ms=10.0,
    ) as srv:
        model_p50, model_p99 = run_load(
            srv, "model", {"img": img}, n_requests=15
        )
        decomp = srv.workers[0].stage_summary()  # queue/lock/handler split
    return triv_p50, triv_p99, model_p50, model_p99, decomp


def run_smoke(out_path: str = "BENCH_pr03.json") -> dict:
    """Dataplane smoke bench (CPU-safe, tiny shapes; wired into tier-1 via
    tests/test_bench_smoke.py). Measures stage-boundary host<->device
    TRANSFER and COMPILE counts for:

    - tpu_model_chain: a fused featurize -> TPUModel chain, device-resident
      vs the pre-change dataflow (every stage boundary materializes host
      numpy and re-uploads). Resident transfer counts must be strictly
      below the baseline's (ISSUE 3 acceptance).
    - serving_ragged: 50 distinct request sizes through a two-stage serving
      handler chain, device-resident + power-of-two bucketing vs the
      pre-change flow (host round-trip at the interior boundary, every
      request padded to the full max_batch). Resident transfer counts AND
      upload bytes must be strictly below; each stage compiles at most
      log2(max_batch)+1 = 8 programs.

    Counts come from profiling.dataplane_counters() — the same meters the
    runtime exports — so the bench measures the product path, not a mock.
    """
    import jax

    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.core.dispatch import bucketing, dispatch_cache
    from mmlspark_tpu.core.pipeline import PipelineModel
    from mmlspark_tpu.dnn import mlp
    from mmlspark_tpu.dnn.network import NetworkBundle
    from mmlspark_tpu.models import TPUModel
    from mmlspark_tpu.utils.profiling import dataplane_counters

    dispatch_cache().clear()  # deterministic compile counts
    counters = dataplane_counters()
    rng = np.random.default_rng(0)

    def tpu_stage(in_dim, out_dim, in_col, out_col, bs, seed, hidden=17):
        net = mlp(in_dim, [hidden], out_dim)
        bundle = NetworkBundle(net, net.init(jax.random.PRNGKey(seed)))
        return TPUModel(bundle, input_col=in_col, output_col=out_col,
                        mini_batch_size=bs)

    # -- fused two-stage chain ------------------------------------------------
    featurize = tpu_stage(8, 13, "features", "embedding", 32, 0)
    head = tpu_stage(13, 4, "embedding", "scores", 32, 1)
    pipeline = PipelineModel([featurize, head])
    df = DataFrame.from_dict(
        {"features": rng.normal(size=(24, 8)).astype(np.float32)}
    )

    def host_roundtrip_with(pm, frame):
        """Pre-change dataflow: materialize host numpy at every boundary."""
        cur = frame
        for stage in pm.get_stages():
            cur = stage.transform(cur)
            cur = DataFrame.from_dict({n: np.asarray(cur[n]) for n in cur.columns})
        return cur

    pipeline.transform(df)  # warm: compiles + weight uploads
    before = counters.snapshot()
    out = pipeline.transform(df)
    np.asarray(out["scores"])  # the single legitimate exit fetch
    resident = counters.delta(before)

    host_roundtrip_with(pipeline, df)  # warm
    before = counters.snapshot()
    out = host_roundtrip_with(pipeline, df)
    np.asarray(out["scores"])
    baseline = counters.delta(before)

    # -- serving-style ragged batches -----------------------------------------
    # The realistic serving handler is itself a chain (parse -> featurize ->
    # model -> reply); 50 distinct request sizes drive it. Pre-change, every
    # request paid the interior host round-trip AND padded to the full
    # max_batch; post-change the interior boundary is device-resident and
    # uploads are right-sized to the power-of-two bucket.
    from mmlspark_tpu.models.tpu_model import forward_program_count

    sizes = [int(n) for n in np.random.default_rng(1).permutation(np.arange(1, 129))[:50]]

    def serving_chain(hidden_a, hidden_b, seed):
        # distinct layer widths per chain -> distinct program keys, so each
        # pass's compile count is its own (the cache is process-wide)
        feat = tpu_stage(6, 9, "features", "embedding", 128, seed, hidden_a)
        hd = tpu_stage(9, 3, "embedding", "scores", 128, seed + 1, hidden_b)
        return PipelineModel([feat, hd])

    def ragged_pass(pm, roundtrip):
        before = counters.snapshot()
        for n in sizes:
            frame = DataFrame.from_dict({"features": np.ones((n, 6), np.float32)})
            scored = host_roundtrip_with(pm, frame) if roundtrip else pm.transform(frame)
            np.asarray(scored["scores"])  # per-request reply sync
        return counters.delta(before)

    serve_pm = serving_chain(21, 23, seed=2)
    bucketed = ragged_pass(serve_pm, roundtrip=False)
    # forward_program_count sums the donating + plain dispatch variants —
    # the honest per-stage program count under donation-backed dispatch
    programs_per_stage = max(
        forward_program_count(s.get_model().network)
        for s in serve_pm.get_stages()
    )
    with bucketing(False):  # pre-change policy: pad every batch to the cap
        fixed_pad = ragged_pass(serving_chain(25, 27, seed=4), roundtrip=True)

    report = {
        "pr": 3,
        "platform": jax.default_backend(),
        "tpu_model_chain": {
            "rows": 24,
            "resident": resident,
            "baseline_host_roundtrip": baseline,
        },
        "serving_ragged": {
            "distinct_sizes": len(set(sizes)),
            "max_batch": 128,
            "max_programs_per_stage": programs_per_stage,
            "bucketed_resident": bucketed,
            "baseline_fixed_pad_roundtrip": fixed_pad,
        },
    }
    return _write_report(report, out_path)


def _closed_loop_load(port, route, n_clients, n_requests, payload_fn,
                      errors_tag="serving load"):
    """Shared closed-loop HTTP harness for the serving smokes: n_clients
    keep-alive clients, n_requests each, payload_fn(cid) -> body bytes.
    Returns (wall seconds, sorted per-request latencies)."""
    import http.client
    import threading

    lat, errors, lock = [], [], threading.Lock()

    def client(cid):
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            body = payload_fn(cid)
            for _ in range(n_requests):
                t0 = time.perf_counter()
                conn.request("POST", route, body,
                             {"Content-Type": "application/json"})
                r = conn.getresponse()
                r.read()
                dt = time.perf_counter() - t0
                with lock:
                    if r.status != 200:
                        errors.append(r.status)
                    else:
                        lat.append(dt)
            conn.close()
        except Exception as e:  # surface, don't die silently
            with lock:
                errors.append(repr(e))

    threads = [
        threading.Thread(target=client, args=(i,)) for i in range(n_clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors or not lat:
        raise RuntimeError(f"{errors_tag} errors: {errors[:5]}")
    return wall, sorted(lat)


def run_serving_smoke(out_path: str = "BENCH_pr04.json") -> dict:
    """Serving-engine smoke bench (CPU-safe; wired into tier-1 via
    tests/test_bench_smoke.py): closed-loop 4-client throughput + latency
    for the SAME staged handler on the synchronous micro-batch engine vs
    the pipelined engine (ISSUE 4 acceptance: >=1.3x throughput, p99 no
    worse), written to BENCH_pr04.json.

    The handler is the real staged path — parse_request + parse-stage h2d
    upload, a jitted matmul in the score stage (run under
    jax.transfer_guard("disallow_explicit") on the pipelined engine), reply-stage
    d2h sync + make_reply — with each host stage's per-row cost padded by a
    short sleep (PER_ROW_S) so the measured ratio reflects the engines'
    overlap structure, not CI-host kernel speed. Real JSON parse/serialize
    cost is per-row too; the sync engine serializes parse+score+reply under
    one lock while the pipelined engine overlaps them across batches, which
    is exactly the effect being measured.
    """
    import http.client

    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.core.dataframe import DataType
    from mmlspark_tpu.serving import (
        ServingServer,
        StagedServingHandler,
        make_reply,
        parse_request,
    )

    # per-row host cost must dominate engine hop overhead (thread wakeups,
    # GIL scheduling, ~1ms/hop) or the comparison measures noise: 5 ms/row
    # keeps the smoke deterministic on slow CI hosts while staying fast
    PER_ROW_S = 5e-3
    DIM = 16
    N_CLIENTS = 4
    N_REQUESTS = 25

    class _SmokeStaged(StagedServingHandler):
        def __init__(self):
            self._w = jax.device_put(
                np.random.default_rng(0).normal(size=(DIM, DIM)).astype(np.float32)
            )
            self._fn = jax.jit(lambda w, x: jnp.tanh(x @ w))

        def parse(self, df):
            parsed = parse_request(df, {"x": DataType.VECTOR})
            time.sleep(PER_ROW_S * len(df))  # emulated per-row decode cost
            parsed.column("x").device_values()  # the parse-stage upload
            return parsed

        def score(self, df):  # device dispatch only: transfer-guard clean
            y = self._fn(self._w, df.column("x").device_values())
            time.sleep(PER_ROW_S * len(df))  # emulated device latency
            return df.with_column("y", y, DataType.VECTOR)

        def reply(self, df):
            time.sleep(PER_ROW_S * len(df))  # emulated per-row encode cost
            return make_reply(df, "y")  # .values inside = the d2h sync

    def closed_loop(port, n_requests):
        return _closed_loop_load(
            port, "/engine", N_CLIENTS, n_requests,
            lambda cid: json.dumps({"x": [float(cid)] * DIM}).encode(),
            errors_tag="serving smoke",
        )

    handler = _SmokeStaged()  # ONE handler: both engines share compiles

    def engine_run(engine):
        # identical knobs for both engines; the short coalescing window is
        # the latency-serving config (sync throughput is batch-size
        # invariant under per-row costs, so it takes no handicap from it)
        with ServingServer(
            handler, api_name="engine", mode="micro_batch", engine=engine,
            max_batch_size=N_CLIENTS, max_wait_ms=2.0,
            guard_score=(engine == "pipelined"),
        ) as srv:
            closed_loop(srv.port, 6)  # warm compiles for every batch size
            wall, lat = closed_loop(srv.port, N_REQUESTS)
            stats = {
                "throughput_rps": round(N_CLIENTS * N_REQUESTS / wall, 1),
                "p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
                "p99_ms": round(lat[int(len(lat) * 0.99)] * 1e3, 3),
                "wall_s": round(wall, 3),
            }
            summary = srv.stage_summary()
            stats["mean_batch_size"] = summary.get("mean_batch_size", 1.0)
            if engine == "pipelined":
                stats["pipeline"] = srv.pipeline_summary()
                stats["expired_in_flight"] = srv.expired_in_flight
        return stats

    sync_stats = engine_run("sync")
    pipe_stats = engine_run("pipelined")
    report = {
        "pr": 4,
        "platform": jax.default_backend(),
        "serving_engines": {
            "workload": {
                "clients": N_CLIENTS,
                "requests_per_client": N_REQUESTS,
                "per_row_host_ms": PER_ROW_S * 1e3,
                "dim": DIM,
            },
            "sync": sync_stats,
            "pipelined": pipe_stats,
            "throughput_speedup": round(
                pipe_stats["throughput_rps"] / sync_stats["throughput_rps"], 3
            ),
        },
    }
    return _write_report(report, out_path)


def run_obs_overhead_smoke(out_path: str = "BENCH_pr05.json") -> dict:
    """Observability-overhead smoke bench (CPU-safe; wired into tier-1 via
    tests/test_bench_smoke.py): the SAME staged serving workload measured
    with the full observability layer on (metrics registry + request
    tracing, the default) vs `obs.disabled()` (every instrument and span a
    no-op). ISSUE 5 acceptance: instrumentation costs <= 5% closed-loop
    throughput, `GET /metrics` scrapes and parses mid-load with the
    required families present, `GET /healthz` returns live engine state,
    and a traced request yields the full http -> parse -> score -> reply
    span tree exportable as Chrome trace events. Written to BENCH_pr05.json.

    Per-row host cost is padded (PER_ROW_S) exactly like run_serving_smoke
    so the ratio reflects instrumentation overhead against a realistic
    request cost, not against an empty loop where any fixed cost looks
    enormous."""
    import http.client

    import jax
    import jax.numpy as jnp

    from mmlspark_tpu import obs
    from mmlspark_tpu.core.dataframe import DataType
    from mmlspark_tpu.serving import (
        ServingServer,
        StagedServingHandler,
        make_reply,
        parse_request,
    )

    PER_ROW_S = 3e-3
    DIM = 16
    N_CLIENTS = 4
    N_REQUESTS = 20

    class _ObsStaged(StagedServingHandler):
        def __init__(self):
            self._w = jax.device_put(
                np.random.default_rng(0).normal(size=(DIM, DIM)).astype(np.float32)
            )
            self._fn = jax.jit(lambda w, x: jnp.tanh(x @ w))

        def parse(self, df):
            parsed = parse_request(df, {"x": DataType.VECTOR})
            time.sleep(PER_ROW_S * len(df))
            parsed.column("x").device_values()
            return parsed

        def score(self, df):
            y = self._fn(self._w, df.column("x").device_values())
            time.sleep(PER_ROW_S * len(df))
            return df.with_column("y", y, DataType.VECTOR)

        def reply(self, df):
            time.sleep(PER_ROW_S * len(df))
            return make_reply(df, "y")

    def closed_loop(port, n_requests):
        return _closed_loop_load(
            port, "/obs", N_CLIENTS, n_requests,
            lambda cid: json.dumps({"x": [float(cid)] * DIM}).encode(),
            errors_tag="obs smoke",
        )

    def http_get(port, route):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", route)
        r = conn.getresponse()
        body = r.read()
        conn.close()
        return r.status, body

    handler = _ObsStaged()  # shared: both arms reuse the same compiles

    def measure(instrumented: bool):
        ctx = contextlib.nullcontext() if instrumented else obs.disabled()
        with ctx:
            with ServingServer(
                handler, api_name="obs", mode="micro_batch",
                max_batch_size=N_CLIENTS, max_wait_ms=2.0,
            ) as srv:
                closed_loop(srv.port, 5)  # warm compiles per batch size
                wall, lat = closed_loop(srv.port, N_REQUESTS)
                stats = {
                    "throughput_rps": round(N_CLIENTS * N_REQUESTS / wall, 1),
                    "p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
                    "p99_ms": round(lat[int(len(lat) * 0.99)] * 1e3, 3),
                    "wall_s": round(wall, 3),
                }
                if instrumented:
                    # the acceptance surfaces, exercised against the LIVE
                    # loaded server: scrape parses, health is green
                    from mmlspark_tpu.obs.metrics import parse_prometheus

                    code, body = http_get(srv.port, "/metrics")
                    assert code == 200, code
                    samples = parse_prometheus(body.decode("utf-8"))
                    names = {name for name, _ in samples}
                    required = {
                        "serving_request_latency_ms_count",
                        "serving_stage_busy_seconds_total",
                        "serving_stage_occupancy",
                        "dataplane_h2d_transfers_total",
                        "dataplane_d2h_transfers_total",
                        "dataplane_compiles_total",
                    }
                    stats["metrics_scrape"] = {
                        "samples": len(samples),
                        "required_present": sorted(required - names) == [],
                    }
                    code, body = http_get(srv.port, "/healthz")
                    health = json.loads(body)
                    stats["healthz"] = {
                        "code": code,
                        "status": health.get("status"),
                        "threads_alive": all(
                            health.get("threads", {}).values()
                        ),
                    }
        return stats

    from mmlspark_tpu.obs import tracer

    tracer().clear()  # the trace assertions below want THIS run's spans
    # Alternate arms and keep the best round of each: a fixed order would
    # bill cold-process warm-up (imports, thread-pool spin-up, first-run
    # scheduler state) to whichever arm ran first — measured at up to ~25%
    # phantom "overhead" on a cold CI process, versus ~0% once warm.
    rounds = [
        measure(instrumented=True), measure(instrumented=False),
        measure(instrumented=True), measure(instrumented=False),
    ]
    instrumented = max(rounds[0], rounds[2],
                       key=lambda s: s["throughput_rps"])
    disabled = max(rounds[1], rounds[3], key=lambda s: s["throughput_rps"])
    # span-tree acceptance: some request from the instrumented runs carries
    # the full stage path, and it exports to Chrome trace events
    span_names_by_trace: dict = {}
    for s in tracer().spans():
        span_names_by_trace.setdefault(s.trace_id, set()).add(s.name)
    full = [
        tid for tid, names in span_names_by_trace.items()
        if {"http", "parse", "score", "reply"} <= names
    ]
    trace_report = {"full_span_trees": len(full)}
    if full:
        events = tracer().chrome_trace(full[0])["traceEvents"]
        trace_report["chrome_events"] = len(events)
        trace_report["chrome_span_names"] = sorted(
            {e["name"] for e in events if e["ph"] == "X"}
        )

    speed_ratio = (
        instrumented["throughput_rps"] / disabled["throughput_rps"]
    )
    report = {
        "pr": 5,
        "platform": jax.default_backend(),
        "obs_overhead": {
            "workload": {
                "clients": N_CLIENTS,
                "requests_per_client": N_REQUESTS,
                "per_row_host_ms": PER_ROW_S * 1e3,
                "dim": DIM,
            },
            "instrumented": instrumented,
            "disabled": disabled,
            "throughput_ratio": round(speed_ratio, 4),
            "overhead_frac": round(max(0.0, 1.0 - speed_ratio), 4),
            "trace": trace_report,
        },
    }
    return _write_report(report, out_path)


def run_fault_smoke(out_path: str = "BENCH_pr06.json") -> dict:
    """Fault-tolerance smoke bench (CPU-safe; wired into tier-1 via
    tests/test_bench_smoke.py): the serving fabric's acceptance scenarios
    (ISSUE 6), written to BENCH_pr06.json.

    - kill_1_of_4: closed-loop load over a 4-worker pool; worker 2 is
      killed mid-load (listening socket abruptly closed). Gate: client
      error rate < 1%, the router ejects the dead worker in < 500 ms,
      p99 stays bounded.
    - wedge_breaker: worker 1 stops answering (accepted-but-wedged,
      injected at the transport). Gate: its circuit breaker trips, traffic
      rebalances with error rate < 1% and bounded p99.
    - overload_shed: offered load at 4x the admission limit. Gate: excess
      sheds as fast 429s while the p99 of ADMITTED requests stays within
      2x of the unloaded baseline (shedding protects the served traffic).
    - replace_under_load: replace_worker() hot-swaps a worker mid-load.
      Gate: zero failed requests (the drain flushes in-flight first).

    Faults come from serving/faults.py — kill closes real sockets, the
    wedge raises the same socket.timeout a real unresponsive peer produces
    — so the gateway code under test cannot tell the scenarios from
    production failures.
    """
    import http.client
    import itertools
    import threading

    import jax

    from mmlspark_tpu.core.dataframe import DataType
    from mmlspark_tpu.serving import (
        DistributedServingServer,
        FabricConfig,
        FaultInjector,
        make_reply,
        parse_request,
    )

    def echo_factory(delay_s=0.002):
        def factory():
            def handler(df):
                time.sleep(delay_s)
                parsed = parse_request(df, {"x": None})
                vals = np.asarray([float(v) * 2.0 for v in parsed["x"]])
                return make_reply(
                    parsed.with_column("y", vals, DataType.DOUBLE), "y"
                )
            return handler
        return factory

    def tolerant_load(port, api, n_clients, n_requests, on_request=None):
        """Closed-loop load that RECORDS failures instead of raising (the
        whole point is measuring the error rate under faults). Returns
        (statuses, sorted 200-latencies seconds)."""
        statuses, lat, lock = [], [], threading.Lock()
        counter = itertools.count()

        def client(cid):
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            conn.connect()  # untimed: measure requests, not SYN handshakes
            body = json.dumps({"x": float(cid)}).encode()
            for _ in range(n_requests):
                seq = next(counter)
                if on_request is not None:
                    on_request(seq)
                t0 = time.perf_counter()
                try:
                    conn.request("POST", f"/{api}", body,
                                 {"Content-Type": "application/json"})
                    r = conn.getresponse()
                    r.read()
                    status = r.status
                except OSError:
                    status = -1  # transport failure at the client
                    conn.close()
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", port, timeout=30
                    )
                dt = time.perf_counter() - t0
                with lock:
                    statuses.append(status)
                    if status == 200:
                        lat.append(dt)
            conn.close()

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return statuses, sorted(lat)

    def stats(statuses, lat):
        bad = [s for s in statuses if s != 200]
        return {
            "requests": len(statuses),
            "errors": len(bad),
            "error_rate": round(len(bad) / max(1, len(statuses)), 4),
            "p50_ms": round(lat[len(lat) // 2] * 1e3, 3) if lat else None,
            "p99_ms": (
                round(lat[int(len(lat) * 0.99)] * 1e3, 3) if lat else None
            ),
        }

    fast = FabricConfig(
        failure_threshold=2, open_secs=0.2, health_interval_s=0.05,
        backoff_base_ms=1.0, backoff_max_ms=5.0,
    )

    # -- scenario 1: kill 1 of 4 under closed-loop load ------------------------
    faults = FaultInjector()
    t_kill = [None]
    with DistributedServingServer(
        echo_factory(), n_workers=4, api_name="fault",
        fabric=fast, worker_timeout=0.5, fault_injector=faults,
    ) as srv:
        warm, _ = tolerant_load(srv.port, "fault", 4, 4)
        assert all(s == 200 for s in warm), warm

        kill_at = 80  # ~1/4 through the 8x40 load

        def maybe_kill(seq):
            if seq == kill_at:
                t_kill[0] = time.monotonic()  # the fabric's clock
                faults.kill_worker(srv, 2)

        statuses, lat = tolerant_load(
            srv.port, "fault", 8, 40, on_request=maybe_kill
        )
        kill_stats = stats(statuses, lat)
        # recovery = kill -> the router's OWN first observation that the
        # worker is unroutable (health flip or breaker open); event-driven,
        # so measurement-thread scheduling can't inflate it
        ejected_at = srv.fabric.unroutable_since(2)
        kill_stats["recovery_ms"] = (
            round((ejected_at - t_kill[0]) * 1e3, 1)
            if ejected_at is not None and t_kill[0] is not None else None
        )
        kill_stats["router"] = srv.fabric.snapshot()["workers"]

    # -- scenario 2: wedged worker trips its breaker ---------------------------
    faults = FaultInjector()
    with DistributedServingServer(
        echo_factory(), n_workers=4, api_name="wedge",
        fabric=fast, worker_timeout=0.25, fault_injector=faults,
    ) as srv:
        tolerant_load(srv.port, "wedge", 4, 4)  # warm
        faults.wedge_worker(1)
        statuses, lat = tolerant_load(srv.port, "wedge", 8, 30)
        snap = srv.fabric.snapshot()
        wedge_stats = stats(statuses, lat)
        wedge_stats["breaker_worker1"] = snap["workers"][1]["breaker"]
        wedge_stats["breaker_tripped"] = snap["workers"][1]["breaker"] in (
            "open", "half_open"
        )

    # -- scenario 3: overload sheds, admitted traffic stays fast ---------------
    shed_cfg = FabricConfig(
        admission_initial=4, admission_min=4, admission_max=4,
        failure_threshold=2, open_secs=0.2,
    )
    with DistributedServingServer(
        echo_factory(delay_s=0.02), n_workers=1, api_name="shed",
        fabric=shed_cfg, worker_timeout=5.0,
    ) as srv:
        tolerant_load(srv.port, "shed", 2, 3)  # warm
        base_statuses, base_lat = tolerant_load(srv.port, "shed", 4, 15)
        over_statuses, over_lat = tolerant_load(srv.port, "shed", 16, 15)
        overload_stats = {
            "baseline": stats(base_statuses, base_lat),
            "overloaded": stats(over_statuses, over_lat),
            "shed_429": sum(1 for s in over_statuses if s == 429),
            "p99_ratio_vs_baseline": (
                round(
                    over_lat[int(len(over_lat) * 0.99)]
                    / base_lat[int(len(base_lat) * 0.99)],
                    3,
                )
                if over_lat and base_lat else None
            ),
        }

    # -- scenario 4: hot swap under load, zero failures ------------------------
    with DistributedServingServer(
        echo_factory(), n_workers=4, api_name="swap", fabric=fast,
        worker_timeout=2.0,
    ) as srv:
        tolerant_load(srv.port, "swap", 4, 4)  # warm
        swap_ms = [None]

        def maybe_swap(seq):
            if seq == 60:
                t0 = time.perf_counter()
                srv.replace_worker(0)
                swap_ms[0] = round((time.perf_counter() - t0) * 1e3, 1)

        statuses, lat = tolerant_load(
            srv.port, "swap", 6, 30, on_request=maybe_swap
        )
        swap_stats = stats(statuses, lat)
        swap_stats["swap_ms"] = swap_ms[0]

    report = {
        "pr": 6,
        "platform": jax.default_backend(),
        "fault_tolerance": {
            "kill_1_of_4": kill_stats,
            "wedge_breaker": wedge_stats,
            "overload_shed": overload_stats,
            "replace_under_load": swap_stats,
        },
    }
    return _write_report(report, out_path)


def run_image_prep_smoke(out_path: str = "BENCH_pr07.json") -> dict:
    """Image-dataplane smoke bench (CPU-safe small shapes; wired into
    tier-1 via tests/test_bench_smoke.py), written to BENCH_pr07.json.

    ISSUE 7 evidence, measured through the product path (no mocks):

    - fused_prep: the fused device resize+unroll program
      (images/device_ops.py, one upload + one XLA program) vs the pre-PR7
      per-row host loop (`for img: ops.resize(img); transpose; reshape` —
      the dataflow behind BENCH_r05's 279 imgs/sec). Gate: >= 2.5x (CI
      scheduler-noise headroom under the ~10x typically measured; the
      ISSUE's >= 3x acceptance is the e2e TPU-harness number).
    - featurize_e2e: decode INCLUDED — a BINARY image column through
      ImageFeaturizer fused=True vs an explicit emulation of the pre-PR7
      per-row decode/resize/unroll prep feeding the same TPUModel.
      Gate: >= 1.5x imgs/sec at CPU smoke scale. The CPU floor is real:
      decode and the model forward are SHARED costs both paths pay, and on
      a 2-core smoke box XLA's forward occupies the same cores the per-row
      loop does, so e2e compression is bounded by prep's share of total
      time (component breakdown here: per-row prep ~60% of the baseline).
      The ISSUE's full >= 3x acceptance rides the TPU harness (bench.main),
      where prep was ~96% of the 279 imgs/sec baseline's cost
      (BENCH_r05: 279 e2e vs 6,375 device-resident).
    - prefetch: the double-buffered host->HBM loader (core/prefetch.py) vs
      the same decode+upload+compute executed serially, on a consumer whose
      device compute OUTWEIGHS decode (the TPU-shaped regime). Gate: the
      ISSUE's overlap proof — upload of batch N+1 completes before batch
      N's compute finishes (shared perf_counter timeline) — for most
      batches, with throughput no worse than serial minus scheduler noise.
    - bf16: zoo ResNet-50 geometry (scaled input) scored in bfloat16 vs
      float32 through TPUModel(dtype=...). Gate: top-1 identical and
      relative logit MAE < BF16_LOGIT_MAE_TOL; the speedup is recorded,
      not gated (bf16 only pays on MXU hardware).
    """
    import jax

    from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType
    from mmlspark_tpu.core.prefetch import DeviceBatchPrefetcher
    from mmlspark_tpu.core.schema import make_image_row
    from mmlspark_tpu.dnn import resnet_mini
    from mmlspark_tpu.dnn.network import Network, NetworkBundle
    from mmlspark_tpu.dnn.zoo_builders import (
        BF16_LOGIT_MAE_TOL,
        resnet50_random,
    )
    from mmlspark_tpu.images import ImageFeaturizer, device_ops, ops
    from mmlspark_tpu.io.image import decode_image, encode_image
    from mmlspark_tpu.models import TPUModel
    from mmlspark_tpu.models.tpu_model import _compiled_forward

    rng = np.random.default_rng(0)
    report: dict = {}

    def _npy_bytes(img):
        import io as _io

        buf = _io.BytesIO()
        np.save(buf, img)
        return buf.getvalue()

    # -- 1. fused device prep vs the per-row host loop -----------------------
    n, src, dst = 192, 96, 48
    imgs = rng.integers(0, 256, (n, src, src, 3), dtype=np.uint8)
    stages = [{"op": "resize", "height": dst, "width": dst}]
    fused = device_ops.fused_prep_program(stages, unroll=True)
    jax.block_until_ready(fused(device_ops.upload_batch(imgs)))  # warm

    def fused_once():
        t0 = time.perf_counter()
        jax.block_until_ready(fused(device_ops.upload_batch(imgs)))
        return time.perf_counter() - t0

    def per_row_once():
        # the pre-PR7 dataflow: one Python iteration per image
        t0 = time.perf_counter()
        out = np.empty((n, dst * dst * 3), np.float64)
        for i in range(n):
            r = ops.resize(imgs[i], dst, dst)
            out[i] = np.transpose(r, (2, 0, 1)).reshape(-1)
        return time.perf_counter() - t0

    fused_s = min(fused_once() for _ in range(3))
    per_row_s = min(per_row_once() for _ in range(3))
    report["fused_prep"] = {
        "images": n,
        "per_row_imgs_per_sec": round(n / per_row_s, 1),
        "fused_imgs_per_sec": round(n / fused_s, 1),
        "speedup": round(per_row_s / fused_s, 2),
    }

    # -- 2. end-to-end featurize, decode included ----------------------------
    # a deliberately light head so the measurement isolates the PREP path
    # (the forward is a shared cost both dataflows pay identically)
    spec = [
        {"kind": "conv", "filters": 8, "kernel": 3, "stride": 4,
         "name": "stem"},
        {"kind": "relu", "name": "act"},
        {"kind": "global_avg_pool", "name": "pool"},
        {"kind": "dense", "units": 8, "name": "logits"},
    ]
    net = Network(spec, (dst, dst, 3))
    bundle = NetworkBundle(net, net.init(jax.random.PRNGKey(0)))
    blobs = np.empty(n, object)
    blobs[:] = [_npy_bytes(im) for im in imgs]
    df = DataFrame({"raw": Column(blobs, DataType.BINARY)})
    feat = ImageFeaturizer(model=bundle, input_col="raw",
                           output_col="features", cut_output_layers=1)
    feat.set_mini_batch_size(n)
    feat.transform(df)  # warm: compiles + weight upload

    inner = TPUModel(bundle, input_col="vec", output_col="features",
                     mini_batch_size=n)
    inner.set_output_layer(feat._output_layer())

    def baseline_once():
        # pre-PR7: per-row decode -> per-row resize -> per-row unroll,
        # then the same TPUModel the fused path runs
        t0 = time.perf_counter()
        vecs = np.empty((n, dst * dst * 3), np.float64)
        for i in range(n):
            img = np.asarray(decode_image(bytes(blobs[i]))["data"])
            r = ops.resize(img, dst, dst)
            vecs[i] = np.transpose(r, (2, 0, 1)).reshape(-1)
        frame = DataFrame.from_dict({"vec": vecs})
        out = inner.transform(frame)
        np.asarray(out["features"])  # final read (forces the d2h)
        return time.perf_counter() - t0

    def fused_e2e_once():
        t0 = time.perf_counter()
        out = feat.transform(df)
        np.asarray(out["features"])
        return time.perf_counter() - t0

    baseline_s = min(baseline_once() for _ in range(3))
    fused_e2e_s = min(fused_e2e_once() for _ in range(3))
    report["featurize_e2e"] = {
        "images": n,
        "decode_included": True,
        "per_row_prep_imgs_per_sec": round(n / baseline_s, 1),
        "fused_imgs_per_sec": round(n / fused_e2e_s, 1),
        "speedup": round(baseline_s / fused_e2e_s, 2),
    }

    # -- 3. double-buffered prefetch vs serial decode+upload+compute ---------
    # PNG blobs (real PIL/zlib host codec work) feeding a consumer whose
    # device compute outweighs a batch's decode+upload — the TPU-shaped
    # regime where the prefetcher fully hides the host work. One decode
    # worker: the smoke box is small (often 2 cores shared with XLA), so
    # extra decode threads only contend.
    pf_batches, pf_bs, pf_src = 10, 32, 64
    pf_imgs = rng.integers(
        0, 256, (pf_batches * pf_bs, pf_src, pf_src, 3), dtype=np.uint8
    )
    pf_blobs = [
        encode_image(make_image_row(im, ""), fmt="png") for im in pf_imgs
    ]
    pf_net = resnet_mini(num_classes=8, input_shape=(dst, dst, 3))
    pf_bundle = NetworkBundle(pf_net, pf_net.init(jax.random.PRNGKey(1)))
    fwd = _compiled_forward(pf_net.truncate_at("pool"))
    dev_vars = pf_bundle.device_variables()

    def decode_chunk(chunk):
        return np.stack(
            [np.asarray(decode_image(bytes(b))["data"]) for b in chunk]
        )

    prep = device_ops.fused_prep_program(stages, unroll=False)

    def compute(dev_batch):
        y = fwd(dev_vars, np.float32(1 / 255.0) * prep(dev_batch))
        jax.block_until_ready(y)

    compute(device_ops.upload_batch(pf_imgs[:pf_bs]))  # warm (compiles)

    def serial_run():
        t0 = time.perf_counter()
        for i in range(pf_batches):
            chunk = pf_blobs[i * pf_bs: (i + 1) * pf_bs]
            compute(device_ops.upload_batch(decode_chunk(chunk)))
        return time.perf_counter() - t0

    def prefetch_run():
        windows = []
        pf = DeviceBatchPrefetcher(
            pf_blobs, decode_chunk, batch_size=pf_bs, depth=2, workers=1
        )
        t0 = time.perf_counter()
        with pf:
            for dev_batch in pf:
                c0 = time.perf_counter()
                compute(dev_batch)
                windows.append((c0, time.perf_counter()))
        total = time.perf_counter() - t0
        # the ISSUE's overlap proof: batch N+1's upload completed before
        # batch N's compute finished (timestamps share one perf_counter)
        tl = pf.timeline()
        overlapped = sum(
            1
            for e in tl
            if e["index"] > 0
            and int(e["index"]) - 1 < len(windows)
            and e["upload_done_t"] <= windows[int(e["index"]) - 1][1]
        )
        return total, overlapped, pf.summary()

    serial_s = min(serial_run() for _ in range(2))
    best = None
    for _ in range(2):
        cand = prefetch_run()
        if best is None or cand[0] < best[0]:
            best = cand
    prefetch_s, overlapped, pf_summary = best
    report["prefetch"] = {
        "batches": pf_batches,
        "batch_size": pf_bs,
        "serial_imgs_per_sec": round(pf_batches * pf_bs / serial_s, 1),
        "prefetch_imgs_per_sec": round(pf_batches * pf_bs / prefetch_s, 1),
        "speedup": round(serial_s / prefetch_s, 2),
        "uploads_overlapping_prev_compute": overlapped,
        "overlap_ratio": pf_summary["overlap_ratio"],
    }

    # -- 4. bf16 vs f32 on the zoo flagship geometry -------------------------
    zoo = resnet50_random(num_classes=10, input_shape=(32, 32, 3))
    zx = rng.integers(0, 256, (32, 32 * 32 * 3), dtype=np.uint8)
    zdf = DataFrame.from_dict({"features": zx})
    f32_model = TPUModel(zoo, input_col="features", output_col="o",
                         mini_batch_size=32)
    bf16_model = TPUModel(zoo, input_col="features", output_col="o",
                          mini_batch_size=32, dtype="bfloat16")
    f32_logits = np.asarray(f32_model.transform(zdf)["o"])  # warm + truth
    bf16_logits = np.asarray(bf16_model.transform(zdf)["o"])

    def timed(model):
        t0 = time.perf_counter()
        np.asarray(model.transform(zdf)["o"])
        return time.perf_counter() - t0

    f32_s = min(timed(f32_model) for _ in range(2))
    bf16_s = min(timed(bf16_model) for _ in range(2))
    rel_mae = float(
        np.abs(f32_logits - bf16_logits).mean() / np.abs(f32_logits).mean()
    )
    report["bf16"] = {
        "model": "resnet50_random(10, 32x32x3)",
        "rel_logit_mae": round(rel_mae, 6),
        "tolerance": BF16_LOGIT_MAE_TOL,
        "top1_match": bool(
            (f32_logits.argmax(axis=1) == bf16_logits.argmax(axis=1)).all()
        ),
        "speedup_vs_f32": round(f32_s / bf16_s, 2),
    }

    return _write_report(report, out_path)


def run_recovery_smoke(out_path: str = "BENCH_pr08.json") -> dict:
    """Preemption-recovery smoke bench (CPU-safe; wired into tier-1 via
    tests/test_bench_smoke.py), written to BENCH_pr08.json. ISSUE 8
    evidence, measured through the product path (no mocks):

    - learner_recovery: a TPULearner fit killed at a checkpoint boundary
      (crash injected AFTER the commit rename — kill -9 semantics) and
      resumed must reach the uninterrupted fit's loss trajectory
      (resume_parity_delta, exact on this backend) with recovery
      (verified load + state restore) measured in ms.
    - gbdt_recovery: same for boosting — killed mid-fit, resumed, final
      ensemble predictions bit-compared against the uninterrupted fit.
    - checkpoint_overhead: wall-clock of a checkpointed fit vs the same
      fit with checkpointing off (alternating arms, best-of-3 each, jit
      cache pre-warmed) — the ISSUE gates overhead <= 5%.
    - fault_matrix: every injected storage fault (torn write, crash
      before/after rename, bit flip, ENOSPC) driven against a live store;
      verified load must never surface a corrupt artifact — it falls back
      to the last good generation (checkpoint_resume_total{outcome=
      "fallback"} increments) or commits the new one when the fault hit
      after the commit point.
    """
    import os
    import shutil
    import tempfile

    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.dnn import mlp
    from mmlspark_tpu.gbdt.objectives import make_objective
    from mmlspark_tpu.gbdt.trainer import TrainConfig, train_booster
    from mmlspark_tpu.io.checkpoint import CheckpointStore, pack_arrays
    from mmlspark_tpu.io.storage_faults import (
        InjectedCrash,
        StorageFaultInjector,
        installed,
    )
    from mmlspark_tpu.models import TPULearner
    from mmlspark_tpu.obs.metrics import registry

    work = tempfile.mkdtemp(prefix="bench_recovery_")
    rng = np.random.default_rng(0)

    # -- learner: kill at a checkpoint boundary, resume, compare ----------------
    n, d = 2048, 32
    yl = rng.integers(0, 2, n)
    xl = (rng.normal(size=(n, d)) + yl[:, None] * 1.5).astype(np.float32)
    df = DataFrame.from_dict({"features": xl, "label": yl.astype(np.int64)})

    def learner():
        return TPULearner(mlp(d, [64], 2), epochs=10, batch_size=128,
                          learning_rate=0.1, seed=3)

    learner().fit(df)  # jit warm-up: compile time must not bill either arm
    t0 = time.perf_counter()
    baseline_model = learner().fit(df)
    plain_s = time.perf_counter() - t0
    baseline_losses = baseline_model._loss_history

    kill_dir = os.path.join(work, "learner_kill")
    inj = StorageFaultInjector()
    inj.crash_after_rename(nth=1)  # epochs=10, every=5 -> kill mid-fit
    killed = False
    try:
        with installed(inj):
            learner().fit(df, checkpoint_dir=kill_dir, checkpoint_every=5)
    except InjectedCrash:
        killed = True
    # recovery = verified load + state unpack, the work a preempted pod
    # redoes before training continues
    t0 = time.perf_counter()
    ck = CheckpointStore(kill_dir).load_latest()
    _state = ck.arrays("train_state.npz")
    recovery_ms = (time.perf_counter() - t0) * 1e3
    resumed_losses = learner().fit(
        df, checkpoint_dir=kill_dir, checkpoint_every=5
    )._loss_history
    learner_delta = float(max(
        abs(a - b) for a, b in zip(baseline_losses, resumed_losses)
    ))

    # -- checkpoint overhead (alternating best-of-2 arms) ----------------------
    def timed_fit(ckpt):
        t = time.perf_counter()
        if ckpt:
            learner().fit(df, checkpoint_dir=ckpt, checkpoint_every=5)
        else:
            learner().fit(df)
        return time.perf_counter() - t

    # alternating arms so scheduler drift hits both equally; symmetric
    # best-of-3 per arm (the earlier plain_s timing is reported only)
    arms = {"plain": [], "ckpt": []}
    for round_i in range(3):
        arms["ckpt"].append(
            timed_fit(os.path.join(work, f"overhead{round_i}"))
        )
        arms["plain"].append(timed_fit(None))
    overhead_frac = max(0.0, min(arms["ckpt"]) / min(arms["plain"]) - 1.0)

    # -- gbdt: kill mid-boosting, resume, bit-compare --------------------------
    ng, fg = 2000, 10
    xg = rng.normal(size=(ng, fg))
    yg = (xg[:, 0] + 0.5 * xg[:, 1] ** 2
          + rng.normal(scale=0.2, size=ng) > 0.5).astype(np.float64)

    def gfit(ckpt=None):
        cfg = TrainConfig(num_iterations=12, num_leaves=15, verbosity=0,
                          bagging_fraction=0.8, bagging_freq=2)
        return train_booster(
            xg, yg, make_objective("binary", num_class=2), cfg,
            checkpoint_dir=ckpt, checkpoint_every=6,
        )

    gfit(os.path.join(work, "gwarm"))  # warm both segment program shapes
    t0 = time.perf_counter()
    g_base = gfit()
    g_plain_s = time.perf_counter() - t0
    pg = np.asarray(g_base.predict_raw(xg))

    g_kill = os.path.join(work, "gbdt_kill")
    ginj = StorageFaultInjector()
    ginj.crash_after_rename(nth=1)
    g_killed = False
    try:
        with installed(ginj):
            gfit(g_kill)
    except InjectedCrash:
        g_killed = True
    t0 = time.perf_counter()
    g_resumed = gfit(g_kill)
    g_resume_s = time.perf_counter() - t0
    gbdt_delta = float(np.max(np.abs(np.asarray(
        g_resumed.predict_raw(xg)) - pg)))
    t0 = time.perf_counter()
    gfit(os.path.join(work, "g_over"))
    g_ckpt_s = time.perf_counter() - t0
    g_overhead = max(0.0, g_ckpt_s / g_plain_s - 1.0)

    # -- storage fault matrix ---------------------------------------------------
    fallback_fam = registry().counter(
        "checkpoint_resume_total", "Checkpoint load outcomes", ("outcome",)
    )

    def fallbacks():
        return fallback_fam.labels(outcome="fallback").value()

    payload_old = {"w.npz": pack_arrays({"w": np.arange(64.0)}),
                   "meta.json": b'{"v": 1}'}
    payload_new = {"w.npz": pack_arrays({"w": np.arange(64.0) * 2}),
                   "meta.json": b'{"v": 2}'}
    matrix = {}
    for fault in ("torn_write", "crash_before_rename", "crash_after_rename",
                  "bit_flip", "enospc"):
        root = os.path.join(work, f"fault_{fault}")
        finj = StorageFaultInjector()
        st = CheckpointStore(root, fault_injector=finj)
        st.save(payload_old)
        fb0 = fallbacks()
        crashed = survived_error = False
        if fault == "bit_flip":
            # silent media corruption of a COMMITTED generation: the write
            # succeeds; only verified load can catch it
            st.save(payload_new)
            StorageFaultInjector.bit_flip(
                os.path.join(st._gen_dir(2), "w.npz"))
        else:
            if fault == "torn_write":
                finj.torn_write("w.npz", at_byte=9)
            elif fault == "crash_before_rename":
                finj.crash_before_rename()
            elif fault == "crash_after_rename":
                finj.crash_after_rename()
            elif fault == "enospc":
                finj.enospc("w.npz")
            try:
                st.save(payload_new)
            except InjectedCrash:
                crashed = True
            except OSError:
                survived_error = True
        ck = CheckpointStore(root).load_latest()
        loaded = ck.json("meta.json")["v"] if ck is not None else None
        expect_new = fault == "crash_after_rename"
        matrix[fault] = {
            "crashed": crashed,
            "live_error": survived_error,
            "loaded_version": loaded,
            "fell_back": fallbacks() > fb0,
            "green": (
                loaded == (2 if expect_new else 1)
                and (fault not in ("bit_flip",) or fallbacks() > fb0)
            ),
        }
    shutil.rmtree(work, ignore_errors=True)

    report = {
        "learner_recovery": {
            "killed_mid_fit": killed,
            "resume_parity_delta": learner_delta,
            "recovery_ms": round(recovery_ms, 3),
            "epochs": 10,
            "checkpoint_every": 5,
        },
        "gbdt_recovery": {
            "killed_mid_fit": g_killed,
            "resume_parity_delta": gbdt_delta,
            "resumed_fit_seconds": round(g_resume_s, 3),
            "iterations": 12,
            "checkpoint_every": 6,
        },
        "checkpoint_overhead": {
            "learner_plain_seconds": round(min(arms["plain"]), 3),
            "learner_ckpt_seconds": round(min(arms["ckpt"]), 3),
            "learner_overhead_frac": round(overhead_frac, 4),
            "gbdt_overhead_frac": round(g_overhead, 4),
        },
        "fault_matrix": matrix,
    }
    return _write_report(report, out_path)


def run_streaming_smoke(out_path: str = "BENCH_pr09.json") -> dict:
    """Streaming-ingestion / out-of-core GBDT smoke bench (CPU-safe; wired
    into tier-1 via tests/test_bench_smoke.py), written to BENCH_pr09.json.
    ISSUE 9 evidence, measured through the product path (no mocks):

    - footprint: the streamed fit (shard reader -> chunked binning ->
      spilled wire-format chunks -> per-pass device streaming) against the
      in-memory fit (load all shards + fused fit) on a dataset 8x the
      chunk budget. Peak host allocation per arm is measured with
      tracemalloc (numpy buffer hooks; resettable, scheduler-free — unlike
      ru_maxrss, which is monotonic across arms and recorded for reference
      only), jit caches pre-warmed so one-time trace/compile transients
      are not billed as data footprint (the PR 8 discipline). Device-side
      the prefetcher's resident-bytes high-water shows the depth-bounded
      HBM footprint.
    - wall_clock: the streamed fit must cost <= 1.3x the in-memory fit at
      smoke scale (it is usually FASTER here: the fused in-memory loop
      re-traces its whole-program scan per shape while the streamed path
      runs small per-chunk kernels).
    - transfers: dataplane counters prove chunked upload discipline — a
      constant number of counted uploads per chunk visit (the 5 payload
      leaves: bins/grad/hess/mask/assign), never a per-row h2d.
    - prefetch: a slow-reader arm (staged delay per chunk behind a slower
      consumer) must hide staging behind compute with overlap_ratio >=
      0.8, timestamp-proven.
    - parity: rerunning the streamed fit is bit-identical; streamed vs
      in-memory predictions agree within f32 chunk-accumulation noise
      (trees_bit_identical records whether the fixed-order accumulation
      achieved full bit-parity on this run, per the ISSUE's
      "state which" requirement).
    - checkpoint_compose: a streamed fit killed at a checkpoint boundary
      (PR 8 storage fault harness, kill -9 semantics after the commit
      rename) resumes to the uninterrupted streamed fit bit-exactly.
    """
    import os
    import shutil
    import tempfile
    import tracemalloc

    from mmlspark_tpu.core.prefetch import DeviceChunkPrefetcher
    from mmlspark_tpu.gbdt.objectives import make_objective
    from mmlspark_tpu.gbdt.trainer import (
        TrainConfig,
        train_booster,
        train_booster_from_reader,
    )
    from mmlspark_tpu.io.columnar import write_numpy_shards
    from mmlspark_tpu.io.storage_faults import (
        InjectedCrash,
        StorageFaultInjector,
        installed,
    )
    from mmlspark_tpu.obs.metrics import registry
    from mmlspark_tpu.utils.profiling import dataplane_counters

    n, F = 49_152, 32
    chunk_rows = 6_144           # dataset = 8x the chunk budget
    rng = np.random.default_rng(0)
    work = tempfile.mkdtemp(prefix="bench_streaming_")
    x = rng.normal(size=(n, F))
    y = (x[:, 0] + 0.5 * x[:, 1] - 0.3 * x[:, 2]
         + rng.normal(scale=0.5, size=n) > 0).astype(np.float64)
    cols = {f"f{j}": x[:, j] for j in range(F)}
    cols["label"] = y
    reader = write_numpy_shards(os.path.join(work, "shards"), cols,
                                chunk_rows * 2)
    reader.chunk_rows = chunk_rows
    fc = [f"f{j}" for j in range(F)]
    del x, cols
    cfg = TrainConfig(num_iterations=3, num_leaves=9, max_bin=31,
                      verbosity=0)
    # the in-memory REFERENCE arm stays the fused engine (this bench's
    # documented comparison target since PR 9); at this row count
    # engine="auto" would now pick the PR 15 data-parallel engine, which
    # has its own bench (BENCH_pr15.json) — pinning keeps the footprint/
    # wall ratios comparable across rounds. The streamed arm keeps auto
    # and therefore shards its chunk stream over the test mesh (PR 15
    # sharded ingestion), which is bit-identical to unsharded streaming.
    import dataclasses as _dc

    cfg_mem = _dc.replace(cfg, engine="fused")
    obj = make_objective("binary", num_class=2)

    def load_all():
        xs = np.concatenate(
            [c.matrix(fc, np.float64) for c in reader.iter_chunks()]
        )
        ys = np.concatenate(
            [np.asarray(c.columns["label"], np.float64)
             for c in reader.iter_chunks()]
        )
        return xs, ys

    def inmem_arm():
        xs, ys = load_all()
        return train_booster(xs, ys, obj, cfg_mem)

    def streamed_arm():
        return train_booster_from_reader(reader, fc, obj, cfg)

    # warm round: pays every trace/compile once AND doubles as the
    # determinism reference (reruns must be bit-identical)
    warm_mem = inmem_arm()
    warm_str = streamed_arm()

    visits_fam = registry().counter(
        "gbdt_stream_chunk_visits_total",
        "Chunk device passes made by streamed GBDT histogram/routing")
    resident_gauge = registry().gauge(
        "dataplane_prefetch_resident_bytes_peak",
        "High-water mark of device bytes parked in the prefetch queue "
        "for the most recently finished prefetch loop (the depth-bounded "
        "HBM footprint of streaming ingestion)")

    tracemalloc.start()
    c0, _ = tracemalloc.get_traced_memory()
    tracemalloc.reset_peak()
    t0 = time.perf_counter()
    b_mem = inmem_arm()
    t_mem = time.perf_counter() - t0
    _, pk = tracemalloc.get_traced_memory()
    peak_mem = pk - c0

    before_dp = dataplane_counters().snapshot()
    before_visits = visits_fam.value()
    c0, _ = tracemalloc.get_traced_memory()
    tracemalloc.reset_peak()
    t0 = time.perf_counter()
    b_str = streamed_arm()
    t_str = time.perf_counter() - t0
    _, pk = tracemalloc.get_traced_memory()
    peak_str = pk - c0
    tracemalloc.stop()
    dp = dataplane_counters().delta(before_dp)
    visits = int(visits_fam.value() - before_visits)

    # parity + determinism (exact comparisons, no retry dependence)
    det_delta = 0.0 if (
        b_str.model_to_string() == warm_str.model_to_string()
    ) else float("nan")
    xt = np.random.default_rng(1).normal(size=(4096, F))
    pm = np.asarray(b_mem.predict_raw(xt))
    ps = np.asarray(b_str.predict_raw(xt))
    max_raw_delta = float(np.max(np.abs(pm - ps)))
    bit_identical = b_str.model_to_string() == b_mem.model_to_string()

    # -- slow-reader prefetch overlap arm ----------------------------------
    def slow_stage(i):
        time.sleep(0.02)         # simulated shard read/decode latency
        return np.full((chunk_rows // 4,), i, np.float32)

    pf = DeviceChunkPrefetcher(iter(range(10)), slow_stage, depth=2)
    with pf:
        for _batch in pf:
            time.sleep(0.025)    # device compute hiding the next stage
    overlap = pf.summary()

    # -- PR 8 composition: kill at a checkpoint boundary, resume ----------
    kd = os.path.join(work, "kill")
    inj = StorageFaultInjector()
    inj.crash_after_rename(nth=1)
    killed = False
    try:
        with installed(inj):
            train_booster_from_reader(
                reader, fc, obj, cfg, checkpoint_dir=kd, checkpoint_every=2
            )
    except InjectedCrash:
        killed = True
    resumed = train_booster_from_reader(
        reader, fc, obj, cfg, checkpoint_dir=kd, checkpoint_every=2
    )
    resume_identical = (
        resumed.model_to_string() == b_str.model_to_string()
    )

    import resource

    shutil.rmtree(work, ignore_errors=True)
    report = {
        "config": {
            "rows": n, "features": F, "chunk_rows": chunk_rows,
            "n_chunks": -(-n // chunk_rows),
            "iterations": cfg.num_iterations, "num_leaves": cfg.num_leaves,
            "max_bin": cfg.max_bin,
        },
        "footprint": {
            "inmem_peak_mb": round(peak_mem / 1e6, 2),
            "streamed_peak_mb": round(peak_str / 1e6, 2),
            "peak_ratio": round(peak_str / max(peak_mem, 1), 4),
            "measured_with": "tracemalloc (numpy buffer hooks), "
                             "jit pre-warmed, per-arm baseline-subtracted",
            "ru_maxrss_mb_monotonic": round(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1
            ),
            "device_resident_bytes_peak": int(resident_gauge.value()),
        },
        "wall_clock": {
            "inmem_fit_s": round(t_mem, 3),
            "streamed_fit_s": round(t_str, 3),
            "ratio": round(t_str / max(t_mem, 1e-9), 3),
        },
        "transfers": {
            "chunk_visits": visits,
            "h2d_transfers": dp["h2d_transfers"],
            "h2d_bytes": dp["h2d_bytes"],
            "uploads_per_visit": round(
                dp["h2d_transfers"] / max(visits, 1), 2
            ),
            "payload_leaves": 5,  # bins / grad / hess / mask / assign
            "per_row_h2d": bool(dp["h2d_transfers"] >= n),
        },
        "prefetch": overlap,
        "parity": {
            "determinism_delta": det_delta,
            "max_raw_delta": max_raw_delta,
            "trees_bit_identical": bit_identical,
        },
        "checkpoint_compose": {
            "killed_mid_fit": killed,
            "resume_identical": resume_identical,
            "checkpoint_every": 2,
        },
    }
    return _write_report(report, out_path)


def run_profiler_smoke(out_path: str = "BENCH_pr13.json") -> dict:
    """Device-utilization profiler smoke bench (CPU-safe; wired into
    tier-1 via tests/test_bench_smoke.py). ISSUE 13 acceptance, through
    the product path:

    - **MFU cross-check**: on the ResNet-20 forward smoke, the runtime
      ``device_mfu`` gauge (XLA cost-model FLOPs / sampled device seconds,
      obs/profiler.py) must land within the documented tolerance band
      [0.5, 2.0] of bench.py's analytic MFU (hand-counted MACs /
      wall-clock, the pre-PR13 offline method). Both divide by the same
      core/env.py peak table, so the band tests the flops+timing
      accounting, not the peak constant. Measured on this container:
      cost-model flops ~0.93x the analytic MACs and ratio ~0.95.
    - **Overhead**: sampled profiling (1-in-4 here, so sampling genuinely
      fires) on a TPUModel-backed staged serving handler costs <= 5%
      closed-loop throughput vs ``obs.disabled()`` — alternating
      best-of-2 arms per the PR 5/PR 8 protocol.
    - **Flight recorder**: ``GET /debug/flight`` on the LIVE loaded
      server returns parseable JSON whose records carry the full dispatch
      schema and whose monotonic total reconciles exactly with the
      ``tpu_model_dispatch_rows`` dispatch counter over the measured
      window; ``GET /debug/trace`` returns valid Chrome trace_event JSON.
    """
    import http.client

    import jax

    from mmlspark_tpu import obs
    from mmlspark_tpu.core.dataframe import DataFrame, DataType
    from mmlspark_tpu.core.env import peak_flops_per_sec
    from mmlspark_tpu.dnn import resnet20_cifar
    from mmlspark_tpu.dnn.network import Network, NetworkBundle
    from mmlspark_tpu.models import TPUModel
    from mmlspark_tpu.obs import device_profiler, profiler_sampling
    from mmlspark_tpu.obs.metrics import registry as obs_registry
    from mmlspark_tpu.serving import (
        ServingServer,
        StagedServingHandler,
        make_reply,
        parse_request,
    )

    MFU_BAND = (0.5, 2.0)  # documented: docs/observability.md
    prof = device_profiler()

    # -- (1) runtime vs analytic MFU on the ResNet-20 forward smoke ----------
    N, B = 256, 128
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, size=(N, 32 * 32 * 3), dtype=np.uint8)
    df = DataFrame.from_dict({"images": imgs})
    net = resnet20_cifar(num_classes=10)
    model = TPUModel(
        NetworkBundle(net, net.init(jax.random.PRNGKey(0))),
        input_col="images", output_col="scores", mini_batch_size=B,
    )
    label = "tpu_model:" + "x".join(str(d) for d in net.input_shape)
    with profiler_sampling(1):  # time EVERY dispatch: the cross-check run
        model.transform(df.limit(B))  # warm: compile + cost-model harvest
        t0 = time.perf_counter()
        out = model.transform(df)
        np.asarray(out["scores"])  # materialize: the analytic arm's clock
        wall = time.perf_counter() - t0
    imgs_per_sec = N / wall
    peak = peak_flops_per_sec()
    # peak is 0.0 on an unknown device kind (env contract: omit MFU rather
    # than report a wrong one) — mirror the ratio's -1.0 "unknown" marker.
    analytic_mfu = (
        imgs_per_sec * net.flops_per_example() / peak if peak > 0 else -1.0
    )
    runtime_mfu = prof.mfu(label)
    mfu_ratio = runtime_mfu / analytic_mfu if analytic_mfu > 0 else -1.0
    cost_recs = [
        r for r in prof.flight()["records"]
        if r["model"] == label and r["flops_source"] is not None
    ]
    mfu_report = {
        "imgs_per_sec": round(imgs_per_sec, 1),
        "peak_flops_per_sec": peak,
        "analytic_mfu": round(analytic_mfu, 5),
        "runtime_mfu": round(runtime_mfu, 5),
        "ratio_runtime_vs_analytic": round(mfu_ratio, 4),
        "tolerance_band": list(MFU_BAND),
        "flops_source": cost_recs[-1]["flops_source"] if cost_recs else None,
        "arithmetic_intensity": (
            round(cost_recs[-1]["flops"] / cost_recs[-1]["bytes"], 2)
            if cost_recs and cost_recs[-1]["bytes"] else None
        ),
    }

    # -- (2) sampled-profiling serving overhead vs obs.disabled() ------------
    PER_ROW_S = 3e-3
    DIM = 16
    N_CLIENTS = 4
    N_REQUESTS = 20
    SAMPLE_EVERY = 4  # sampling must actually fire inside the measured run

    snet = Network(
        [{"kind": "dense", "units": 32}, {"kind": "dense", "units": 8}],
        (DIM,),
    )
    smodel = TPUModel(
        NetworkBundle(snet, snet.init(jax.random.PRNGKey(1))),
        input_col="x", output_col="scored", mini_batch_size=N_CLIENTS,
    )

    class _ProfStaged(StagedServingHandler):
        """The real dispatch path under load: score IS TPUModel.transform,
        so sampled device timing, flight records and cost capture all ride
        the measured hot path (per-row host cost padded like the PR 4/5
        smokes so the ratio reflects profiler overhead against realistic
        request cost, not an empty loop)."""

        def parse(self, df):
            parsed = parse_request(df, {"x": (DataType.VECTOR, DIM)})
            time.sleep(PER_ROW_S * len(df))
            parsed.column("x").device_values()
            return parsed

        def score(self, df):
            out = smodel.transform(df)
            time.sleep(PER_ROW_S * len(df))
            return out

        def reply(self, df):
            time.sleep(PER_ROW_S * len(df))
            return make_reply(df, "scored")

    def closed_loop(port, n_requests):
        return _closed_loop_load(
            port, "/prof", N_CLIENTS, n_requests,
            lambda cid: json.dumps({"x": [float(cid)] * DIM}).encode(),
            errors_tag="profiler smoke",
        )

    def http_get(port, route):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", route)
        r = conn.getresponse()
        body = r.read()
        conn.close()
        return r.status, body

    handler = _ProfStaged()  # shared: both arms reuse the same compiles
    dispatch_rows_hist = obs_registry().histogram(
        "tpu_model_dispatch_rows",
        "Padded rows per TPUModel device dispatch",
    )

    def measure(instrumented: bool):
        ctx = contextlib.nullcontext() if instrumented else obs.disabled()
        with ctx, profiler_sampling(SAMPLE_EVERY):
            with ServingServer(
                handler, api_name="prof", mode="micro_batch",
                max_batch_size=N_CLIENTS, max_wait_ms=2.0,
            ) as srv:
                closed_loop(srv.port, 5)  # warm compiles per batch size
                flight_before = prof.flight()["total_records"]
                rows_before = dispatch_rows_hist.count()
                wall, lat = closed_loop(srv.port, N_REQUESTS)
                stats = {
                    "throughput_rps": round(N_CLIENTS * N_REQUESTS / wall, 1),
                    "p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
                    "p99_ms": round(lat[int(len(lat) * 0.99)] * 1e3, 3),
                    "wall_s": round(wall, 3),
                }
                if instrumented:
                    # flight recorder acceptance, against the LIVE server:
                    # parseable JSON, full record schema, and the monotonic
                    # total reconciling exactly with the dispatch counter
                    # over the measured window
                    code, body = http_get(srv.port, "/debug/flight")
                    assert code == 200, code
                    flight = json.loads(body)
                    recs = flight["records"]
                    fields = {
                        "site", "model", "program", "signature", "rows",
                        "t_queue", "t_dispatch", "t_done", "device_s",
                        "sampled", "flops", "flops_source", "bytes",
                        "donated", "cache_hit", "trace_id",
                    }
                    stats["flight"] = {
                        "records": len(recs),
                        "total_records": flight["total_records"],
                        "ring_capacity": flight["ring_capacity"],
                        "schema_complete": all(
                            fields <= set(r) for r in recs
                        ),
                        "window_dispatches": (
                            flight["total_records"] - flight_before
                        ),
                        "window_dispatch_counter": (
                            dispatch_rows_hist.count() - rows_before
                        ),
                        "sampled_records": sum(
                            1 for r in recs if r["sampled"]
                        ),
                        "traced_records": sum(
                            1 for r in recs if r["trace_id"]
                        ),
                    }
                    code, body = http_get(srv.port, "/debug/trace")
                    assert code == 200, code
                    trace = json.loads(body)
                    events = trace.get("traceEvents")
                    stats["chrome_trace"] = {
                        "events": len(events),
                        "valid": isinstance(events, list) and all(
                            {"name", "ph", "ts", "pid"} <= set(e)
                            for e in events
                        ),
                    }
        return stats

    # alternating best-of-2 arms (the PR 5/PR 8 protocol): a fixed order
    # would bill cold-process warm-up to whichever arm ran first
    rounds = [
        measure(instrumented=True), measure(instrumented=False),
        measure(instrumented=True), measure(instrumented=False),
    ]
    instrumented = max(rounds[0], rounds[2],
                       key=lambda s: s["throughput_rps"])
    disabled = max(rounds[1], rounds[3], key=lambda s: s["throughput_rps"])
    speed_ratio = instrumented["throughput_rps"] / disabled["throughput_rps"]

    report = {
        "pr": 13,
        "platform": jax.default_backend(),
        "mfu": mfu_report,
        "profiler_overhead": {
            "workload": {
                "clients": N_CLIENTS,
                "requests_per_client": N_REQUESTS,
                "per_row_host_ms": PER_ROW_S * 1e3,
                "dim": DIM,
                "sample_every": SAMPLE_EVERY,
            },
            "instrumented": instrumented,
            "disabled": disabled,
            "throughput_ratio": round(speed_ratio, 4),
            "overhead_frac": round(max(0.0, 1.0 - speed_ratio), 4),
        },
    }
    return _write_report(report, out_path)


def run_slo_trace_smoke(out_path: str = "BENCH_pr14.json") -> dict:
    """Fabric-tracing + SLO burn-rate smoke bench (CPU-safe; wired into
    tier-1 via tests/test_bench_smoke.py), written to BENCH_pr14.json.
    ISSUE 14 acceptance, through the product path (no mocks):

    - **trace_propagation**: closed-loop load over a 2-worker gateway with
      worker 0 WEDGED (accepts, never answers; the injected transport
      raises the same socket.timeout a real unresponsive peer produces) —
      a retried request's assembled cross-process tree (gateway root ->
      >=2 attempt children -> worker http -> parse/score/reply) is
      fetched BY TRACE ID from ``GET /debug/trace?trace_id=`` on the
      gateway, and tail retention pinned the retried trace.
    - **slo**: against a fresh healthy pool, an injected error burst
      (handler raises -> worker 500s forwarded by the gateway) fires the
      fast-window burn alert (`slo_burn_alerts_total{slo,window}` with
      exemplar trace ids) and flips ``/healthz`` on the gateway AND at
      least one worker to ``"degraded"`` (HTTP code stays 200 — a burning
      pool is still the place to send traffic), while a latency-SLO
      control over the same stream does not alert; once the burst stops,
      the short window drains and health returns to ``ok`` — the
      multi-window construction resetting promptly by design.
    - **overhead**: tracing + SLO evaluation cost <= 5% closed-loop
      serving throughput vs ``obs.disabled()`` (alternating best-of-2
      arms, the PR 5/8/13 protocol).
    """
    import http.client

    import jax
    import jax.numpy as jnp

    from mmlspark_tpu import obs
    from mmlspark_tpu.core.dataframe import DataType
    from mmlspark_tpu.obs import tracer
    from mmlspark_tpu.obs.metrics import registry as obs_reg
    from mmlspark_tpu.obs.slo import BurnWindow, SLOSpec, slo_monitor
    from mmlspark_tpu.serving import (
        DistributedServingServer,
        FabricConfig,
        FaultInjector,
        ServingServer,
        StagedServingHandler,
        make_reply,
        parse_request,
    )

    def http_get(port, route):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", route)
        r = conn.getresponse()
        body = r.read()
        conn.close()
        return r.status, body

    def echo_factory():
        def handler(df):
            parsed = parse_request(df, {"x": None})
            vals = []
            for v in parsed["x"]:
                if v == "boom":  # the injected error burst's trigger
                    raise RuntimeError("injected error burst")
                vals.append(float(v) * 2.0)
            return make_reply(
                parsed.with_column(
                    "y", np.asarray(vals, np.float64), DataType.DOUBLE
                ),
                "y",
            )
        return handler

    def post(port, api, payload):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        body = json.dumps(payload).encode()
        conn.request("POST", f"/{api}", body,
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        r.read()
        tid = r.getheader("X-Trace-Id")
        conn.close()
        return r.status, tid

    fast_fabric = FabricConfig(
        failure_threshold=3, open_secs=0.3, health_interval_s=0.05,
        backoff_base_ms=1.0, backoff_max_ms=4.0,
    )
    monitor = slo_monitor()

    # -- (1) one trace id across the fabric under a wedged worker ------------
    tracer().clear()
    faults = FaultInjector()
    with DistributedServingServer(
        echo_factory, n_workers=2, api_name="slotrace",
        mode="micro_batch", max_wait_ms=2.0,
        fabric=fast_fabric, worker_timeout=0.25, fault_injector=faults,
    ) as srv:
        for i in range(8):  # warm: both workers touched, compiles paid
            post(srv.port, "slotrace", {"x": 1.0})
        # wedge the worker traffic is herding to (lowest EWMA wins the
        # p2c tie-break), so routed requests deterministically hit the
        # wedge, time out, and retry against the healthy worker
        snap = srv.fabric.snapshot()["workers"]
        wedged = min(
            snap,
            key=lambda w: (
                w["ewma_ms"] if w["ewma_ms"] is not None else float("inf")
            ),
        )["idx"]
        faults.wedge_worker(wedged)
        for i in range(10):
            post(srv.port, "slotrace", {"x": float(i)})
        # find a retried request's trace in the shared ring, then fetch
        # its ASSEMBLED tree over HTTP by trace id (the product surface)
        by_trace: dict = {}
        for s in tracer().spans():
            by_trace.setdefault(s.trace_id, []).append(s.name)
        retried = next(
            (
                tid for tid, names in by_trace.items()
                if names.count("attempt") >= 2
                and "gateway" in names
                and {"http", "parse", "score", "reply"} <= set(names)
            ),
            None,
        )
        assert retried is not None, "no retried cross-process trace found"
        code, body = http_get(
            srv.port, f"/debug/trace?trace_id={retried}"
        )
        assert code == 200, code
        tree = json.loads(body)
        roots = tree["roots"]
        root = roots[0] if roots else {"name": None, "children": []}
        attempts = [
            c for c in root.get("children", []) if c["name"] == "attempt"
        ]
        worker_stages: set = set()
        for a in attempts:
            for c in a["children"]:
                if c["name"] == "http":
                    worker_stages |= {g["name"] for g in c["children"]}
        tree_report = {
            "trace_id": retried,
            "roots": len(roots),
            "root_name": root.get("name"),
            "attempt_children": len(attempts),
            "worker_stage_names": sorted(worker_stages),
            "cross_process_tree": bool(
                len(roots) == 1
                and root.get("name") == "gateway"
                and len(attempts) >= 2
                and {"parse", "score", "reply"} <= worker_stages
            ),
            "pinned_flag": tree.get("flag"),
        }

    # -- (2) SLO burn: error burst -> fast alert -> degraded -> recovered ----
    fastw = BurnWindow("fast", short_s=1.5, long_s=6.0,
                       burn_threshold=2.0, severity="page")
    sloww = BurnWindow("slow", short_s=3.0, long_s=12.0,
                       burn_threshold=1.0, severity="ticket")
    alerts_fam = obs_reg().counter(
        "slo_burn_alerts_total",
        "Multi-window burn-rate alert activations per SLO",
        ("slo", "window"),
    )
    spec_names = []
    prev_interval = monitor.eval_interval_s
    try:
        with DistributedServingServer(
            echo_factory, n_workers=2, api_name="sloburn",
            mode="micro_batch", max_wait_ms=2.0, fabric=fast_fabric,
            worker_timeout=5.0,
        ) as srv:
            gw_label = srv.fabric.gateway_label
            monitor.eval_interval_s = 0.05
            specs = [
                SLOSpec("gw_availability", objective="availability",
                        target=0.95, engine=gw_label,
                        windows=(fastw, sloww), min_events=8),
                SLOSpec("latency_control", objective="latency",
                        target=0.95, latency_threshold_ms=60_000.0,
                        engine=gw_label, windows=(fastw, sloww),
                        min_events=8),
            ] + [
                SLOSpec(f"worker{i}_availability",
                        objective="availability", target=0.95,
                        engine=w._obs_label, windows=(fastw, sloww),
                        min_events=4)
                for i, w in enumerate(srv.workers)
            ]
            for spec in specs:
                monitor.register(spec)
                spec_names.append(spec.name)

            def alert_count(slo, window="fast"):
                return alerts_fam.labels(slo=slo, window=window).value()

            before = {s: alert_count(s) for s in spec_names}
            for _ in range(12):  # healthy baseline traffic
                post(srv.port, "sloburn", {"x": 1.0})
            monitor.evaluate()
            code0, body0 = http_get(srv.port, "/healthz")
            health_before = json.loads(body0)

            burst = [
                post(srv.port, "sloburn", {"x": "boom"})[0]
                for _ in range(24)
            ]
            status_after = monitor.evaluate()
            code1, body1 = http_get(srv.port, "/healthz")
            health_after = json.loads(body1)
            worker_statuses = []
            for w in srv.workers:
                wcode, wbody = http_get(w.port, "/healthz")
                worker_statuses.append(
                    (wcode, json.loads(wbody)["status"])
                )
            gw_alert = status_after.get("gw_availability", {})
            exemplars = (
                gw_alert.get("alerts", {})
                .get("fast", {})
                .get("exemplar_trace_ids", [])
            )

            # the burst stops; the SHORT window drains and the alert
            # resolves — multi-window alerting resetting promptly
            time.sleep(fastw.short_s + 0.3)
            for _ in range(12):
                post(srv.port, "sloburn", {"x": 1.0})
            monitor.evaluate()
            code2, body2 = http_get(srv.port, "/healthz")
            health_recovered = json.loads(body2)

            slo_report = {
                "windows": {
                    "fast": [fastw.short_s, fastw.long_s,
                             fastw.burn_threshold],
                    "slow": [sloww.short_s, sloww.long_s,
                             sloww.burn_threshold],
                },
                "burst_500s": sum(1 for s in burst if s >= 500),
                "healthz_before": health_before["status"],
                "fast_alert_fired": (
                    alert_count("gw_availability") - before["gw_availability"]
                ) >= 1,
                "alert_exemplar_trace_ids": len(exemplars),
                "healthz_degraded": bool(
                    code1 == 200 and health_after["status"] == "degraded"
                ),
                "worker_healthz_degraded": any(
                    c == 200 and s == "degraded"
                    for c, s in worker_statuses
                ),
                "control_alerted": (
                    alert_count("latency_control") - before["latency_control"]
                ) >= 1,
                "healthz_recovered_ok": health_recovered["status"] == "ok",
                "error_budget_remaining": status_after.get(
                    "gw_availability", {}
                ).get("error_budget_remaining"),
            }
    finally:
        monitor.eval_interval_s = prev_interval
        for name in spec_names:
            monitor.unregister(name)

    # -- (3) tracing + SLO evaluation overhead vs obs.disabled() -------------
    PER_ROW_S = 3e-3
    DIM = 16
    N_CLIENTS = 4
    N_REQUESTS = 20

    class _SLOStaged(StagedServingHandler):
        def __init__(self):
            self._w = jax.device_put(
                np.random.default_rng(0).normal(
                    size=(DIM, DIM)
                ).astype(np.float32)
            )
            self._fn = jax.jit(lambda w, x: jnp.tanh(x @ w))

        def parse(self, df):
            parsed = parse_request(df, {"x": DataType.VECTOR})
            time.sleep(PER_ROW_S * len(df))
            parsed.column("x").device_values()
            return parsed

        def score(self, df):
            y = self._fn(self._w, df.column("x").device_values())
            time.sleep(PER_ROW_S * len(df))
            return df.with_column("y", y, DataType.VECTOR)

        def reply(self, df):
            time.sleep(PER_ROW_S * len(df))
            return make_reply(df, "y")

    def closed_loop(port, n_requests):
        return _closed_loop_load(
            port, "/slosmoke", N_CLIENTS, n_requests,
            lambda cid: json.dumps({"x": [float(cid)] * DIM}).encode(),
            errors_tag="slo smoke",
        )

    handler = _SLOStaged()  # shared: both arms reuse the same compiles

    def measure(instrumented: bool):
        ctx = contextlib.nullcontext() if instrumented else obs.disabled()
        with ctx:
            with ServingServer(
                handler, api_name="slosmoke", mode="micro_batch",
                max_batch_size=N_CLIENTS, max_wait_ms=2.0,
            ) as srv:
                spec = SLOSpec(
                    f"overhead-{srv._obs_label}",
                    objective="availability", target=0.99,
                    engine=srv._obs_label,
                    windows=(BurnWindow("fast", 1.0, 4.0, 14.4),),
                )
                monitor.register(spec)
                prev = monitor.eval_interval_s
                monitor.eval_interval_s = 0.05
                tracer().set_latency_threshold_ms(250.0)
                try:
                    closed_loop(srv.port, 5)  # warm compiles per batch size
                    wall, lat = closed_loop(srv.port, N_REQUESTS)
                finally:
                    tracer().set_latency_threshold_ms(None)
                    monitor.eval_interval_s = prev
                    monitor.unregister(spec.name)
                return {
                    "throughput_rps": round(
                        N_CLIENTS * N_REQUESTS / wall, 1
                    ),
                    "p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
                    "p99_ms": round(lat[int(len(lat) * 0.99)] * 1e3, 3),
                    "wall_s": round(wall, 3),
                }

    # alternating best-of-2 arms (the PR 5/8/13 protocol): a fixed order
    # would bill cold-process warm-up to whichever arm ran first
    rounds = [
        measure(instrumented=True), measure(instrumented=False),
        measure(instrumented=True), measure(instrumented=False),
    ]
    instrumented = max(rounds[0], rounds[2],
                       key=lambda s: s["throughput_rps"])
    disabled = max(rounds[1], rounds[3], key=lambda s: s["throughput_rps"])
    speed_ratio = instrumented["throughput_rps"] / disabled["throughput_rps"]

    report = {
        "pr": 14,
        "platform": jax.default_backend(),
        "trace_propagation": tree_report,
        "slo": slo_report,
        "overhead": {
            "workload": {
                "clients": N_CLIENTS,
                "requests_per_client": N_REQUESTS,
                "per_row_host_ms": PER_ROW_S * 1e3,
                "dim": DIM,
            },
            "instrumented": instrumented,
            "disabled": disabled,
            "throughput_ratio": round(speed_ratio, 4),
            "overhead_frac": round(max(0.0, 1.0 - speed_ratio), 4),
        },
    }
    return _write_report(report, out_path)


def run_sharded_gbdt_smoke(out_path: str = "BENCH_pr15.json") -> dict:
    """Mesh-sharded data-parallel GBDT smoke bench (8-virtual-device CPU
    mesh; wired into tier-1 via tests/test_bench_smoke.py), written to
    BENCH_pr15.json. ISSUE 15 acceptance, through the product path:

    - **throughput**: at a fixed dataset, the data-parallel engine's
      boosting-loop wall (gbdt_phase_seconds{boost_data_parallel}, jit
      pre-warmed) must be >= 4x faster than the single-device fused fit's
      boosting loop (boost_fused). On this single-core CI box the win is
      work-efficiency — per-shard leaf skipping + small-child-only passes
      vs the fused loop's full-row pass per split (the same mechanism that
      gave PR 9's streamed engine its 0.26x wall ratio); on a real pod the
      per-shard dispatches additionally run concurrently, one per chip.
    - **parity**: the sharded fit is bit-identical to the single-device
      fused fit (model_to_string equality — the explicit fixed-shard-order
      reduction's determinism contract), and reruns are bit-identical.
    - **transfers (resident)**: the dp fit's counted uploads are exactly
      shards x payload leaves (bins/y/raw/assign/mask once per shard) —
      row data uploads ONCE per fit, never per pass, never per row.
    - **streamed-sharded**: the out-of-core engine under chunk->device
      round-robin ownership keeps the PR 9 single-stream footprint bound
      (peak RSS <= 0.5x the in-memory fused fit, tracemalloc) and the
      PR 9 upload discipline (counted uploads == payload leaves x chunk
      visits, zero per-row h2d), while placing chunks across the whole
      mesh (owner_devices records the coverage).
    - **checkpoint_compose**: a sharded fit killed at a checkpoint
      boundary (PR 8 fault harness) resumes bit-identically.
    """
    import os
    import shutil
    import tempfile
    import tracemalloc

    import jax

    from mmlspark_tpu.core.prefetch import DeviceChunkPrefetcher
    from mmlspark_tpu.gbdt import trainer as trainer_mod
    from mmlspark_tpu.gbdt.objectives import make_objective
    from mmlspark_tpu.gbdt.trainer import (
        TrainConfig,
        train_booster,
        train_booster_from_reader,
    )
    from mmlspark_tpu.io.columnar import round_robin_owners, write_numpy_shards
    from mmlspark_tpu.io.storage_faults import (
        InjectedCrash,
        StorageFaultInjector,
        installed,
    )
    from mmlspark_tpu.obs.metrics import registry
    from mmlspark_tpu.utils.profiling import dataplane_counters

    import dataclasses

    nd = jax.device_count()
    if nd < 8:
        # the sharded arms need the 8-way mesh (tests/conftest.py forces
        # it; `python bench.py --smoke` sets the flag before jax loads) —
        # return unwritten so a mis-launched run can't clobber the
        # committed artifact
        return {"skipped": True, "n_devices": nd,
                "reason": "needs XLA_FLAGS=--xla_force_host_platform_"
                          "device_count=8 (set before jax import)"}

    n, F = 49_152, 32
    rng = np.random.default_rng(7)
    x = rng.normal(size=(n, F))
    y = (x[:, 0] + 0.5 * x[:, 1] - 0.3 * x[:, 2]
         + rng.normal(scale=0.5, size=n) > 0).astype(np.float64)
    cfg = TrainConfig(num_iterations=3, num_leaves=9, max_bin=31,
                      verbosity=0)
    dp_cfg = dataclasses.replace(cfg, engine="data_parallel")
    obj = make_objective("binary", num_class=2)
    phase = registry().histogram(
        "gbdt_phase_seconds", "Wall seconds per GBDT training phase",
        ("phase",))
    visits_fam = registry().counter(
        "gbdt_stream_chunk_visits_total",
        "Chunk device passes made by streamed GBDT histogram/routing")

    def fused_single():
        trainer_mod._FORCE_SINGLE_DEVICE = True
        try:
            return train_booster(
                x, y, obj, dataclasses.replace(cfg, engine="fused")
            )
        finally:
            trainer_mod._FORCE_SINGLE_DEVICE = False

    # warm round: pays trace/compile once for both engines; the dp warm
    # fit doubles as the determinism reference
    fused_single()
    warm_dp = train_booster(x, y, obj, dp_cfg)

    # -- timed arms (both under tracemalloc — same measurement conditions;
    # the fused arm's peak is also the streamed footprint baseline) -------
    tracemalloc.start()
    c0, _ = tracemalloc.get_traced_memory()
    tracemalloc.reset_peak()
    s0 = phase.labels(phase="boost_fused").sum()
    t0 = time.perf_counter()
    b_fused = fused_single()
    t_fused = time.perf_counter() - t0
    boost_fused_s = phase.labels(phase="boost_fused").sum() - s0
    _, pk = tracemalloc.get_traced_memory()
    peak_mem = pk - c0

    before_dp_counters = dataplane_counters().snapshot()
    s0 = phase.labels(phase="boost_data_parallel").sum()
    t0 = time.perf_counter()
    b_dp = train_booster(x, y, obj, dp_cfg)
    t_dp = time.perf_counter() - t0
    boost_dp_s = phase.labels(phase="boost_data_parallel").sum() - s0
    dp_tx = dataplane_counters().delta(before_dp_counters)

    # -- streamed-sharded arm (reader -> spill -> chunk->device owners) ---
    work = tempfile.mkdtemp(prefix="bench_sharded_gbdt_")
    cols = {f"f{j}": x[:, j] for j in range(F)}
    cols["label"] = y
    chunk_rows = 6_144
    reader = write_numpy_shards(os.path.join(work, "shards"), cols,
                                chunk_rows * 2)
    reader.chunk_rows = chunk_rows
    fc = [f"f{j}" for j in range(F)]
    train_booster_from_reader(reader, fc, obj, dp_cfg)  # warm
    before_tx = dataplane_counters().snapshot()
    before_visits = visits_fam.value()
    c0, _ = tracemalloc.get_traced_memory()
    tracemalloc.reset_peak()
    t0 = time.perf_counter()
    b_str = train_booster_from_reader(reader, fc, obj, dp_cfg)
    t_str = time.perf_counter() - t0
    _, pk = tracemalloc.get_traced_memory()
    peak_str = pk - c0
    tracemalloc.stop()
    str_tx = dataplane_counters().delta(before_tx)
    visits = int(visits_fam.value() - before_visits)

    # chunk->owner coverage, probed through the same placement machinery
    # the engine uses (the engine's own payload devices are internal)
    owners = round_robin_owners(8, jax.devices())
    seen_devices = set()
    with DeviceChunkPrefetcher(
        iter(range(8)), lambda i: np.ones(64, np.float32),
        placement=lambda i: owners[i],
    ) as pf:
        for dev in pf:
            seen_devices.add(list(dev.devices())[0])

    # -- parity + determinism (exact, deterministic comparisons) ----------
    det_delta = 0.0 if (
        b_dp.model_to_string() == warm_dp.model_to_string()
    ) else float("nan")
    bit_identical = b_dp.model_to_string() == b_fused.model_to_string()
    del b_str  # footprint/transfer arm; parity for it is tier-1-tested

    # -- PR 8 composition: kill at a checkpoint boundary, resume ----------
    xs, ys = x[:12_288], y[:12_288]
    ck_cfg = dataclasses.replace(
        TrainConfig(num_iterations=4, num_leaves=9, max_bin=31,
                    verbosity=0, bagging_fraction=0.8, bagging_freq=2),
        engine="data_parallel")
    base = train_booster(xs, ys, obj, ck_cfg)
    kd = os.path.join(work, "kill")
    inj = StorageFaultInjector()
    inj.crash_after_rename(nth=1)
    killed = False
    try:
        with installed(inj):
            train_booster(xs, ys, obj, ck_cfg, checkpoint_dir=kd,
                          checkpoint_every=2)
    except InjectedCrash:
        killed = True
    resumed = train_booster(xs, ys, obj, ck_cfg, checkpoint_dir=kd,
                            checkpoint_every=2)
    resume_identical = resumed.model_to_string() == base.model_to_string()
    shutil.rmtree(work, ignore_errors=True)

    leaves_per_shard = 5  # bins / y / raw / assign / mask (no weights)
    n_chunks = -(-n // chunk_rows)
    report = {
        "pr": 15,
        "n_devices": nd,
        "config": {
            "rows": n, "features": F, "iterations": cfg.num_iterations,
            "num_leaves": cfg.num_leaves, "max_bin": cfg.max_bin,
            "chunk_rows": chunk_rows, "n_chunks": n_chunks,
        },
        "throughput": {
            "boost_fused_s": round(boost_fused_s, 3),
            "boost_dp_s": round(boost_dp_s, 3),
            "ratio_vs_fused": round(
                boost_fused_s / max(boost_dp_s, 1e-9), 2
            ),
            "fused_fit_s": round(t_fused, 3),
            "dp_fit_s": round(t_dp, 3),
            "hist_rows_per_sec_fused": round(
                n * cfg.num_iterations / max(boost_fused_s, 1e-9), 1
            ),
            "hist_rows_per_sec_dp": round(
                n * cfg.num_iterations / max(boost_dp_s, 1e-9), 1
            ),
            "measured_on": "gbdt_phase_seconds boost-loop wall, jit "
                           "pre-warmed, both arms under tracemalloc",
        },
        "parity": {
            "trees_bit_identical": bit_identical,
            "determinism_delta": det_delta,
        },
        "transfers_dp": {
            "resident_uploads": dp_tx["h2d_transfers"],
            "expected_resident_uploads": leaves_per_shard * nd,
            "payload_leaves_per_shard": leaves_per_shard,
            "h2d_bytes": dp_tx["h2d_bytes"],
            "per_row_h2d": bool(dp_tx["h2d_transfers"] >= n / 10),
        },
        "streamed_sharded": {
            "streamed_fit_s": round(t_str, 3),
            "inmem_peak_mb": round(peak_mem / 1e6, 2),
            "streamed_peak_mb": round(peak_str / 1e6, 2),
            "peak_ratio": round(peak_str / max(peak_mem, 1), 4),
            "chunk_visits": visits,
            "h2d_transfers": str_tx["h2d_transfers"],
            "uploads_per_visit": round(
                str_tx["h2d_transfers"] / max(visits, 1), 2
            ),
            "payload_leaves": 5,  # bins / grad / hess / mask / assign
            "per_row_h2d": bool(str_tx["h2d_transfers"] >= n),
            "owner_devices": len(seen_devices),
        },
        "checkpoint_compose": {
            "killed_mid_fit": killed,
            "resume_identical": resume_identical,
            "checkpoint_every": 2,
            "engine": "data_parallel",
        },
    }
    return _write_report(report, out_path)


def run_memory_smoke(out_path: str = "BENCH_pr16.json") -> dict:
    """Device-memory ledger + shard-skew smoke bench (CPU-safe; wired into
    tier-1 via tests/test_bench_smoke.py::test_memory_smoke_gates). ISSUE
    16 acceptance on the 8-virtual-device mesh:

    - cycle: a featurize->score TPUModel pass uploads weights and retains
      AOT programs, a chunk prefetcher stages payloads — every class shows
      up in the ledger, and evicting (dispatch-cache clear + bundle GC +
      prefetch drain) returns the ledger EXACTLY to its baseline.
    - reconcile: a mid-cycle truth-check against jax.live_arrays() stays
      within tolerance on every device (no phantom drift).
    - leak: a synthetic scratch leak on a tightly-knobbed private ledger
      IS detected — one structured warning naming the offending class.
    - skew: a balanced data-parallel GBDT fit reports shard skew near 1.0;
      a fault-injected 30 ms delay on one shard (_SHARD_DELAY_FN, the
      exact code path a straggling chip would take) trips the persistent
      straggler warning with skew above the configured factor.
    - overhead: the ledger + skew instrumentation costs <= 5% on a
      prefetch-consume + dp-fit workload vs `obs.disabled()` (alternating
      best-of-2 arms, run_obs_overhead_smoke discipline).
    """
    import dataclasses
    import gc

    import jax

    from mmlspark_tpu import obs
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.core.dispatch import dispatch_cache
    from mmlspark_tpu.core.prefetch import DeviceChunkPrefetcher
    from mmlspark_tpu.dnn import mlp
    from mmlspark_tpu.dnn.network import NetworkBundle
    from mmlspark_tpu.gbdt import trainer as trainer_mod
    from mmlspark_tpu.gbdt.objectives import make_objective
    from mmlspark_tpu.gbdt.trainer import TrainConfig, train_booster
    from mmlspark_tpu.models import TPUModel
    from mmlspark_tpu.obs.memory import DeviceMemoryLedger, memory_ledger
    from mmlspark_tpu.obs.metrics import parse_prometheus, registry

    nd = jax.device_count()
    if nd < 8:
        # unwritten skip: a mis-launched single-device run must not
        # clobber the committed 8-way artifact (run_sharded_gbdt_smoke
        # discipline)
        return {"skipped": True, "n_devices": nd,
                "reason": "needs XLA_FLAGS=--xla_force_host_platform_"
                          "device_count=8 (set before jax import)"}

    led = memory_ledger()
    rng = np.random.default_rng(16)

    def metric_value(name, **labels):
        samples = parse_prometheus(registry().render_prometheus())
        want = {(k, str(v)) for k, v in labels.items()}
        for (n, lbls), v in samples.items():
            if n == name and want <= set(lbls):
                return v
        return None

    def cls_total(snap, cls):
        return sum(by.get(cls, 0) for by in snap.values())

    # -- featurize -> score -> evict cycle ------------------------------------
    # settle the process first: programs and dead uploads from earlier
    # smoke sections must not decrement the ledger mid-cycle
    dispatch_cache().clear()
    gc.collect()
    baseline_total = led.total_bytes()
    baseline_snap = led.snapshot()

    net = mlp(8, [17], 4)
    bundle = NetworkBundle(net, net.init(jax.random.PRNGKey(0)))
    model = TPUModel(bundle, input_col="features", output_col="scores",
                     mini_batch_size=32)
    df = DataFrame.from_dict(
        {"features": rng.normal(size=(48, 8)).astype(np.float32)}
    )
    out = model.transform(df)
    np.asarray(out["scores"])  # the one exit fetch

    resident = led.snapshot()
    weights_b = cls_total(resident, "model_weights") - cls_total(
        baseline_snap, "model_weights")
    programs_b = cls_total(resident, "dispatch_programs") - cls_total(
        baseline_snap, "dispatch_programs")

    # prefetch_chunks: resident while staged, drained to zero at exhaustion
    payload = {"bins": np.zeros((512, 16), np.uint8),
               "g": np.zeros(512, np.float32)}
    pf = DeviceChunkPrefetcher(iter(range(6)), lambda i: dict(payload),
                               depth=2)
    it = iter(pf)
    next(it)
    # the first pop frees its chunk immediately; wait for the producer to
    # stage the next window so the class is observably resident
    prefetch_mid = 0
    deadline = time.perf_counter() + 10.0
    while prefetch_mid <= 0 and time.perf_counter() < deadline:
        prefetch_mid = cls_total(
            led.snapshot(), "prefetch_chunks"
        ) - cls_total(baseline_snap, "prefetch_chunks")
        if prefetch_mid <= 0:
            time.sleep(0.005)
    for _ in it:
        pass
    prefetch_end = cls_total(led.snapshot(), "prefetch_chunks") - cls_total(
        baseline_snap, "prefetch_chunks")

    # truth-check while weights + programs are resident
    rec = led.reconcile()
    reconcile_report = {
        "drifted": rec["drifted"],
        "devices_checked": len(rec["devices"]),
        "max_phantom_bytes": max(
            (d["phantom_bytes"] for d in rec["devices"].values()),
            default=0.0,
        ),
    }

    # evict: AOT programs decrement on cache clear, weights on bundle GC
    dispatch_cache().clear()
    del out, model, bundle, df
    gc.collect()
    end_total = led.total_bytes()

    cycle = {
        "baseline_bytes": baseline_total,
        "model_weights_bytes": weights_b,
        "dispatch_programs_bytes": programs_b,
        "prefetch_chunks_mid_bytes": prefetch_mid,
        "prefetch_chunks_end_bytes": prefetch_end,
        "end_bytes": end_total,
        "returned_to_baseline": end_total == baseline_total,
    }

    # -- synthetic leak -------------------------------------------------------
    # private ledger with tight knobs so the detector's thresholds are the
    # bench's, not the deployment defaults; monotonic scratch allocs with
    # no frees are exactly the pattern the trend detector exists for
    leak_led = DeviceMemoryLedger(
        leak_min_samples=8, leak_growth_frac=0.2, leak_min_growth_bytes=4096
    )
    for _ in range(12):
        leak_led.record_alloc("cpu:0", "scratch", 8192, owner="bench:leak")
    events = leak_led.leak_events()
    leak_report = {
        "detected": bool(events),
        "class": events[0]["class"] if events else None,
        "growth_bytes": events[0]["growth_bytes"] if events else 0,
        "warnings": len(events),
    }
    leak_led.clear()

    # -- shard skew + fault-injected straggler --------------------------------
    n, F = 16_384, 16
    x = rng.normal(size=(n, F))
    y = (x[:, 0] + 0.5 * x[:, 1]
         + rng.normal(scale=0.5, size=n) > 0).astype(np.float64)
    dp_cfg = TrainConfig(num_iterations=4, num_leaves=7, max_bin=31,
                         verbosity=0, engine="data_parallel")
    obj = make_objective("binary", num_class=2)

    train_booster(x, y, obj, dp_cfg)  # warm: compiles
    train_booster(x, y, obj, dp_cfg)  # balanced arm on warm programs
    balanced_ratio = metric_value(
        "gbdt_shard_skew_ratio", engine="data_parallel")
    warns_before = metric_value(
        "gbdt_straggler_warnings_total", engine="data_parallel") or 0.0

    trainer_mod._SHARD_DELAY_FN = lambda i: 0.03 if i == 3 else 0.0
    try:
        train_booster(x, y, obj, dp_cfg)
    finally:
        trainer_mod._SHARD_DELAY_FN = None
    straggler_ratio = metric_value(
        "gbdt_shard_skew_ratio", engine="data_parallel")
    warns_after = metric_value(
        "gbdt_straggler_warnings_total", engine="data_parallel") or 0.0

    skew_report = {
        "n_shards": nd,
        "balanced_ratio": (
            round(balanced_ratio, 4) if balanced_ratio is not None else None
        ),
        "factor": 3.0,
        "straggler": {
            "injected_delay_ms": 30.0,
            "ratio": (
                round(straggler_ratio, 4)
                if straggler_ratio is not None else None
            ),
            "warnings_fired": int(warns_after - warns_before),
        },
    }

    # -- instrumentation overhead ---------------------------------------------
    # the ledger-heavy workload: a counted chunk-prefetch consume loop plus
    # one dp mini-fit (skew meter + per-shard ledger) per arm
    ov_payload = {"bins": np.zeros((4096, 32), np.uint8),
                  "g": np.zeros(4096, np.float32)}
    ov_cfg = dataclasses.replace(dp_cfg, num_iterations=2)

    def arm():
        t0 = time.perf_counter()
        pf = DeviceChunkPrefetcher(
            iter(range(24)), lambda i: dict(ov_payload), depth=3)
        for _ in pf:
            time.sleep(1e-3)  # bounded per-chunk consumer cost
        train_booster(x, y, obj, ov_cfg)
        return time.perf_counter() - t0

    train_booster(x, y, obj, ov_cfg)  # warm the 2-iteration programs
    # alternate arms, best-of-2 each: a fixed order would bill warm-up to
    # whichever arm ran first (run_obs_overhead_smoke's measured ~25%
    # phantom overhead on a cold process)
    walls = []
    for instrumented in (True, False, True, False):
        ctx = contextlib.nullcontext() if instrumented else obs.disabled()
        with ctx:
            walls.append(arm())
    instrumented_s = min(walls[0], walls[2])
    disabled_s = min(walls[1], walls[3])
    overhead = {
        "instrumented_s": round(instrumented_s, 4),
        "disabled_s": round(disabled_s, 4),
        "overhead_frac": round(
            max(0.0, instrumented_s / disabled_s - 1.0), 4),
    }

    report = {
        "pr": 16,
        "platform": jax.default_backend(),
        "n_devices": nd,
        "memory": {
            "cycle": cycle,
            "reconcile": reconcile_report,
            "leak": leak_report,
            "skew": skew_report,
            "overhead": overhead,
        },
    }
    return _write_report(report, out_path)


def run_federation_smoke(out_path: str = "BENCH_pr20.json") -> dict:
    """Observability-federation smoke bench (CPU-safe; wired into tier-1
    via tests/test_bench_smoke.py), written to BENCH_pr20.json. ISSUE 20
    acceptance, through the product path (no mocks):

    - **reconciliation**: a 4-worker closed loop, then EXACT equality
      between (a) the federated ``proc="cluster"``
      `serving_request_latency_ms_count` sum over worker engines on the
      gateway's /metrics, (b) the sum of the same series read directly
      off each worker's own /metrics, and (c) the number of requests the
      clients actually completed — federation neither loses nor
      double-counts a single request.
    - **cluster_slo**: an `SLOSpec` registered at the gateway on the
      CLUSTER engine label (`srv.cluster_engine`) — an engine no request
      ever carries directly; only the federation scrape feed populates
      it — fires its fast-window page alert after an injected
      worker-side error burst, and flips the gateway /healthz to
      degraded, from federated data alone.
    - **memory_scope**: ``GET /debug/memory?scope=cluster`` attributes
      every proc's resident bytes with zero drift (per-class sums equal
      the ledger total; the truth-check reports no drifted devices).
    - **kill**: killing one worker mid-run yields PARTIAL cluster debug
      results (an explicit per-worker error entry, no hang), increments
      `obs_federation_scrape_failures_total` for that worker, its
      staleness gauge rises between two reads, and the router snapshot
      flags it `scrape_stale` once past the staleness budget.
    - **overhead**: the whole federation plane (background scrapes +
      merged re-export + SLO feed) costs <= 5% closed-loop serving
      throughput, measured as paired alternating segments on ONE pool
      with the scrape loop running vs stopped (median per arm) — the
      paired design cancels the pool-startup scheduling noise that
      dwarfs a 5% bound when each arm gets its own pool.
    """
    import http.client

    import jax

    from mmlspark_tpu.core.dataframe import DataType
    from mmlspark_tpu.obs.federation import FederationConfig
    from mmlspark_tpu.obs.metrics import parse_prometheus
    from mmlspark_tpu.obs.metrics import registry as obs_reg
    from mmlspark_tpu.obs.slo import BurnWindow, SLOSpec, slo_monitor
    from mmlspark_tpu.serving import (
        DistributedServingServer,
        FabricConfig,
        FaultInjector,
        make_reply,
        parse_request,
    )

    PER_ROW_S = 2e-3
    N_CLIENTS = 4
    N_REQUESTS = 20

    def echo_factory():
        def handler(df):
            parsed = parse_request(df, {"x": None})
            vals = []
            for v in parsed["x"]:
                if v == "boom":  # worker-side error burst trigger
                    raise RuntimeError("injected worker error")
                vals.append(float(v) * 2.0)
            time.sleep(PER_ROW_S * len(df))
            return make_reply(
                parsed.with_column(
                    "y", np.asarray(vals, np.float64), DataType.DOUBLE
                ),
                "y",
            )
        return handler

    def http_get(port, route):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", route)
        r = conn.getresponse()
        body = r.read()
        conn.close()
        return r.status, body

    def post(port, api, payload):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", f"/{api}", json.dumps(payload).encode(),
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        r.read()
        conn.close()
        return r.status

    fast_fabric = FabricConfig(
        failure_threshold=3, open_secs=0.3, health_interval_s=0.05,
        backoff_base_ms=1.0, backoff_max_ms=4.0,
    )
    fed_cfg = FederationConfig(scrape_interval_s=0.1)
    monitor = slo_monitor()

    def serving_counts(text, engines):
        """Sum of serving_request_latency_ms_count over `engines`,
        restricted (for federated text) to proc="cluster" series."""
        total = 0.0
        for (name, labels), v in parse_prometheus(text).items():
            if name != "serving_request_latency_ms_count":
                continue
            lab = dict(labels)
            if "proc" in lab and lab["proc"] != "cluster":
                continue
            if lab.get("engine") in engines:
                total += v
        return total

    # -- (1-4) one 4-worker pool: load, reconcile, burn, kill ----------------
    faults = FaultInjector()
    fastw = BurnWindow("fast", short_s=1.5, long_s=6.0,
                       burn_threshold=2.0, severity="page")
    alerts_fam = obs_reg().counter(
        "slo_burn_alerts_total",
        "Multi-window burn-rate alert activations per SLO",
        ("slo", "window"),
    )
    prev_interval = monitor.eval_interval_s
    spec_name = None
    try:
        with DistributedServingServer(
            echo_factory, n_workers=4, api_name="fedsmoke",
            fabric=fast_fabric, worker_timeout=5.0,
            fault_injector=faults, federation=fed_cfg,
        ) as srv:
            monitor.eval_interval_s = 0.05
            spec = SLOSpec(
                "cluster_availability", objective="availability",
                target=0.95, engine=srv.cluster_engine,
                windows=(fastw,), min_events=8,
            )
            monitor.register(spec)
            spec_name = spec.name
            alerts_before = alerts_fam.labels(
                slo=spec.name, window="fast"
            ).value()

            wall, lat = _closed_loop_load(
                srv.port, "/fedsmoke", N_CLIENTS, N_REQUESTS,
                lambda cid: json.dumps({"x": float(cid)}).encode(),
                errors_tag="federation smoke",
            )
            # quiesce, then read the gateway's federated view (the GET
            # itself refreshes due scrape targets) and every worker's own
            # exposition; traffic has stopped, so the three tallies must
            # agree EXACTLY
            worker_engines = {w._obs_label for w in srv.workers}
            time.sleep(fed_cfg.scrape_interval_s + 0.1)
            code, fed_body = http_get(srv.port, "/metrics")
            assert code == 200, code
            cluster_sum = serving_counts(fed_body.decode(), worker_engines)
            direct_sum = 0.0
            for w in srv.workers:
                wcode, wbody = http_get(w.port, "/metrics")
                assert wcode == 200, wcode
                direct_sum += serving_counts(
                    wbody.decode(), {w._obs_label}
                )
            reconciliation = {
                "clients": N_CLIENTS,
                "requests_per_client": N_REQUESTS,
                "completed_requests": len(lat),
                "cluster_sum": cluster_sum,
                "worker_direct_sum": direct_sum,
                "exact": (
                    cluster_sum == direct_sum == float(len(lat))
                ),
            }

            # worker-side error burst -> the CLUSTER spec (an engine only
            # the federation feed ever populates) pages at the gateway
            burst = [post(srv.port, "fedsmoke", {"x": "boom"})
                     for _ in range(24)]
            time.sleep(fed_cfg.scrape_interval_s + 0.05)
            http_get(srv.port, "/metrics")  # force a scrape -> SLO feed
            status_after = monitor.evaluate()
            _hcode, hbody = http_get(srv.port, "/healthz")
            health = json.loads(hbody)
            cluster_slo = {
                "engine": srv.cluster_engine,
                "burst_500s": sum(1 for s in burst if s >= 500),
                "alert_fired": (
                    alerts_fam.labels(slo=spec.name, window="fast").value()
                    - alerts_before
                ) >= 1,
                "burn_status": status_after.get(spec.name, {}).get(
                    "alerts", {}
                ).get("fast", {}).get("active"),
                "healthz_degraded": health["status"] == "degraded",
                "cluster_slos_served": spec.name in (
                    health.get("cluster_slos") or {}
                ),
            }

            # cluster-scope memory debug: per-proc attribution, zero drift
            _mcode, mbody = http_get(
                srv.port, "/debug/memory?scope=cluster"
            )
            mem = json.loads(mbody)
            drift_free = True
            for payload in mem["procs"].values():
                by_dev = payload["resident"]
                class_sum = sum(
                    b for dev in by_dev.values() for b in dev.values()
                )
                if class_sum != payload["total_bytes"]:
                    drift_free = False
                rec = payload.get("reconcile") or {}
                if rec.get("drifted"):
                    drift_free = False
            memory_scope = {
                "procs": sorted(mem["procs"]),
                "errors": len(mem["errors"]),
                "zero_drift": drift_free and mem["errors"] == [],
            }

            # kill one worker: partial debug results, failure counter,
            # rising staleness, router scrape_stale flag
            faults.kill_worker(srv, 0)
            time.sleep(fed_cfg.scrape_interval_s + 0.05)
            http_get(srv.port, "/metrics")  # scrape round hits the corpse
            _c1, h1 = http_get(srv.port, "/healthz")
            stale_1 = json.loads(h1)["federation"]["targets"]["worker-0"]
            _fcode, fbody = http_get(
                srv.port, "/debug/flight?scope=cluster"
            )
            flight = json.loads(fbody)
            # past the staleness budget the router view flags the worker
            time.sleep(
                fed_cfg.stale_after_intervals * fed_cfg.scrape_interval_s
                + 0.15
            )
            http_get(srv.port, "/metrics")
            _c2, h2 = http_get(srv.port, "/healthz")
            health2 = json.loads(h2)
            stale_2 = health2["federation"]["targets"]["worker-0"]
            router_w0 = next(
                w for w in health2["router"]["workers"] if w["idx"] == 0
            )
            fail_total = sum(
                v for (name, labels), v in parse_prometheus(
                    http_get(srv.port, "/metrics")[1].decode()
                ).items()
                if name == "obs_federation_scrape_failures_total"
                and dict(labels).get("worker") == "worker-0"
                and dict(labels).get("proc") == "cluster"
            )
            kill = {
                "partial_errors": len(flight["errors"]),
                "procs_still_served": len(flight["procs"]),
                "scrape_failures_total": fail_total,
                "staleness_first_s": stale_1["staleness_s"],
                "staleness_later_s": stale_2["staleness_s"],
                "staleness_rising": (
                    stale_2["staleness_s"] > stale_1["staleness_s"] > 0.0
                ),
                "scrape_stale_flagged": bool(router_w0["scrape_stale"]),
            }
    finally:
        monitor.eval_interval_s = prev_interval
        if spec_name is not None:
            monitor.unregister(spec_name)

    # -- (5) federation overhead: paired same-pool arms ----------------------
    # The two arms share ONE 4-worker pool and alternate short segments
    # with the federation scrape loop running ("on") vs stopped ("off").
    # Separate pools per arm proved unmeasurable on a shared box: pool
    # startup scheduling alone swings closed-loop throughput far more
    # than the <=5% bound under test. Pairing the arms on the same pool
    # cancels that noise; the median over 5 segments per arm absorbs any
    # single scheduler stall. Every "on" segment forces a scrape round
    # at its start (plus the 0.5s background cadence — 4x the deployed
    # default of 2s), so the plane is demonstrably active inside every
    # measured "on" window.
    N_OVERHEAD_REQS = 75  # per client per segment (~300 reqs/segment)
    N_OVERHEAD_PAIRS = 5

    def _segment():
        wall, lat = _closed_loop_load(
            srv.port, "/fedov", N_CLIENTS, N_OVERHEAD_REQS,
            lambda cid: json.dumps({"x": float(cid)}).encode(),
            errors_tag="federation overhead",
        )
        return {
            "throughput_rps": round(N_CLIENTS * N_OVERHEAD_REQS / wall, 1),
            "p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
            "p99_ms": round(lat[int(len(lat) * 0.99)] * 1e3, 3),
            "wall_s": round(wall, 3),
        }

    with DistributedServingServer(
        echo_factory, n_workers=4, api_name="fedov", fabric=fast_fabric,
        worker_timeout=5.0,
        federation=FederationConfig(scrape_interval_s=0.5),
    ) as srv:
        assert srv.federator is not None
        _closed_loop_load(
            srv.port, "/fedov", N_CLIENTS, 5,
            lambda cid: json.dumps({"x": float(cid)}).encode(),
            errors_tag="federation overhead warm",
        )
        # absorb the one full-exposition round; in-process workers are
        # identity-probed from here on (the steady-state scrape cost)
        srv.federator.scrape_all(force=True)
        on_segments, off_segments = [], []
        for _ in range(N_OVERHEAD_PAIRS):
            srv.federator.start()
            srv.federator.scrape_all(force=True)
            on_segments.append(_segment())
            srv.federator.stop()
            off_segments.append(_segment())
        srv.federator.start()
        http_get(srv.port, "/metrics")  # federated view still serves

    def _median_rps(segments):
        rps = sorted(s["throughput_rps"] for s in segments)
        return rps[len(rps) // 2]

    enabled_best = max(on_segments, key=lambda s: s["throughput_rps"])
    disabled_best = max(off_segments, key=lambda s: s["throughput_rps"])
    ratio = _median_rps(on_segments) / _median_rps(off_segments)

    report = {
        "pr": 20,
        "platform": jax.default_backend(),
        "federation": {
            "scrape_interval_s": fed_cfg.scrape_interval_s,
            "n_workers": 4,
            "reconciliation": reconciliation,
            "cluster_slo": cluster_slo,
            "memory_scope": memory_scope,
            "kill": kill,
            "overhead": {
                "enabled": enabled_best,
                "disabled": disabled_best,
                "enabled_median_rps": _median_rps(on_segments),
                "disabled_median_rps": _median_rps(off_segments),
                "n_segment_pairs": N_OVERHEAD_PAIRS,
                "throughput_ratio": round(ratio, 4),
                "overhead_frac": round(max(0.0, 1.0 - ratio), 4),
            },
        },
    }
    return _write_report(report, out_path)


def run_dnn_training_smoke(out_path: str = "BENCH_pr18.json") -> dict:
    """Pipelined DNN training smoke bench (CPU-safe; wired into tier-1 via
    tests/test_bench_smoke.py::test_dnn_training_smoke_gates). ISSUE 18
    acceptance on the 8-virtual-device mesh:

    - pipeline: a streamed fit through the async input pipeline
      (fit_from_reader, prefetch_depth=2) against the LEGACY loop this PR
      replaced — upload, dispatch, float(loss) every step, same sharded
      data-parallel step math, same reader stream — with the reader given
      a real per-chunk latency (0.7x the calibrated step time, a lazy
      storage tier). Gate: pipelined wall >= 1.3x faster. The depth-0
      arm (prefetch_depth=0, the rollback lever) must match the
      pipelined loss history EXACTLY (delta 0.0) — the pipeline changes
      scheduling, never arithmetic. NOTE the honest baseline here is the
      per-step-host-sync loop, not depth-0: XLA's async dispatch already
      overlaps reader latency with device compute once nothing forces a
      per-step host sync, so depth-0 rides within a few percent of the
      pipelined arm on this mesh (reported as depth0_wall_s).
    - overlap: staging (slice/pad/cast + upload) keeps ahead of the
      consumer — aggregate overlap ratio (1 - total consumer wait /
      total producer prep) >= 0.8 on an in-memory pipelined fit.
    - uploads: the counted-transfer invariant — one h2d per device-shard
      leaf per batch ({x, y, w} = 3) plus one train-state upload per fit,
      EXACT, and zero per-row transfers or d2h syncs inside the epochs.
    - mfu: the device profiler publishes device_mfu{model=tpu_learner:*}
      from inside the epoch loop.
    - accumulation: accum_steps=4 reruns bit-identically (delta 0.0) and
      stays within a small band of the accum=1 trajectory (f32
      accumulation; reported, not gated exactly).
    - out_of_core: a streamed epoch over disk shards at an 8x-chunk data
      budget peaks at <= 0.6x the traced host allocations of the
      equivalent in-memory fit (tracemalloc, compile-warmed arms).
    - recovery: a streamed fit with accum_steps=2 killed at the first
      checkpoint rename resumes to the uninterrupted trajectory EXACTLY
      (delta 0.0).
    """
    import gc
    import tempfile
    import tracemalloc

    import jax

    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.core.prefetch import upload_host_chunk
    from mmlspark_tpu.dnn import mlp
    from mmlspark_tpu.io.columnar import (
        ArrayReader,
        NumpyShardReader,
        write_numpy_shards,
    )
    from mmlspark_tpu.models import TPULearner
    from mmlspark_tpu.obs.profiler import device_profiler
    from mmlspark_tpu.utils.profiling import dataplane_counters

    nd = jax.device_count()
    if nd < 8:
        # unwritten skip: a mis-launched single-device run must not
        # clobber the committed 8-way artifact
        return {"skipped": True, "n_devices": nd,
                "reason": "needs XLA_FLAGS=--xla_force_host_platform_"
                          "device_count=8 (set before jax import)"}

    N, D, BS, HID, CLASSES = 4096, 64, 256, [256, 256], 8
    rng = np.random.default_rng(18)
    yv = rng.integers(0, CLASSES, N).astype(np.int64)
    xv = (rng.normal(size=(N, D)) + yv[:, None] * 0.3).astype(np.float32)
    df = DataFrame.from_dict({"features": xv, "label": yv})
    steps = N // BS

    def learner(**kw):
        kw.setdefault("epochs", 4)
        kw.setdefault("batch_size", BS)
        kw.setdefault("learning_rate", 0.1)
        kw.setdefault("seed", 7)
        kw.setdefault("shuffle", False)
        return TPULearner(mlp(D, HID, CLASSES), **kw)

    # -- calibration ----------------------------------------------------------
    # first fit pays the XLA compile; afterwards fits only pay trace, so
    # the 1-vs-3-epoch wall difference isolates per-step device time
    learner(epochs=1).fit(df)
    t0 = time.perf_counter()
    learner(epochs=1, prefetch_depth=0).fit(df)
    w1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    learner(epochs=3, prefetch_depth=0).fit(df)
    w3 = time.perf_counter() - t0
    step_s = max(1e-4, (w3 - w1) / (2 * steps))
    delay_s = 0.7 * step_s

    def slow_reader():
        """The reader arm: per-chunk latency a lazy storage tier would
        show (sleep happens in the source pull, exactly where a remote
        read would stall the pre-PR-18 loop)."""
        class _Slow(ArrayReader):
            def iter_chunks(self):
                for c in super().iter_chunks():
                    time.sleep(delay_s)
                    yield c
        return _Slow({"features": xv, "label": yv}, chunk_rows=BS)

    # -- pipeline speedup vs the legacy per-step-host-sync loop ---------------
    EPOCHS = 12
    piped_learner = learner(epochs=EPOCHS, prefetch_depth=2)
    t0 = time.perf_counter()
    piped_model = piped_learner.fit_from_reader(slow_reader())
    piped_wall = time.perf_counter() - t0

    def legacy_sync_epochs():
        """The loop this PR replaced: per-batch upload, jitted sharded
        data-parallel step, float(loss) host sync EVERY step (the exact
        shape graftcheck's per-step-host-sync-in-train-loop rule now
        rejects inside the package). Same network, same momentum-SGD
        update math, same reader stream, same batch sharding."""
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        mesh = Mesh(np.array(jax.devices()), ("data",))
        batch_shard = NamedSharding(mesh, PartitionSpec("data"))
        repl = NamedSharding(mesh, PartitionSpec())
        net = mlp(D, HID, CLASSES)
        variables = net.init(jax.random.PRNGKey(7))
        params = jax.device_put(variables["params"], repl)
        state = jax.device_put(variables["state"], repl)
        vel = jax.tree_util.tree_map(jnp.zeros_like, params)

        def loss_fn(p, s, bx, by, bw):
            out, ns = net.apply_and_state(
                {"params": p, "state": s}, bx, train=True,
                rng=jax.random.PRNGKey(0), sample_weight=bw)
            logp = jax.nn.log_softmax(out)
            per = -jnp.take_along_axis(logp, by[:, None], axis=1)[:, 0]
            return jnp.sum(per * bw) / jnp.maximum(jnp.sum(bw), 1e-9), ns

        @jax.jit
        def step(p, s, v, bx, by, bw):
            (l, ns), g = jax.value_and_grad(
                loss_fn, has_aux=True)(p, s, bx, by, bw)
            v2 = jax.tree_util.tree_map(lambda a, b: 0.9 * a + b, v, g)
            p2 = jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, v2)
            return p2, ns, v2, l

        reader = slow_reader()
        losses = []
        t0 = time.perf_counter()
        for _ in range(EPOCHS):
            total = 0.0
            for chunk in reader.iter_chunks():
                bx = chunk.matrix(["features"], np.float32)
                by = np.rint(chunk.columns["label"]).astype(np.int32)
                bw = np.ones(len(by), np.float32)
                dev = upload_host_chunk(
                    {"x": bx, "y": by, "w": bw}, batch_shard)
                params, state, vel, l = step(
                    params, state, vel, dev["x"], dev["y"], dev["w"])
                total += float(l) * len(by)  # the per-step host sync
            losses.append(total / N)
        return time.perf_counter() - t0, losses

    legacy_wall, _legacy_losses = legacy_sync_epochs()

    # the rollback lever must be bit-identical: depth changes scheduling,
    # never arithmetic
    t0 = time.perf_counter()
    depth0_model = learner(
        epochs=EPOCHS, prefetch_depth=0).fit_from_reader(slow_reader())
    depth0_wall = time.perf_counter() - t0
    loss_delta = max(
        abs(a - b) for a, b in zip(
            piped_model._loss_history, depth0_model._loss_history)
    )

    pipeline = {
        "epochs": EPOCHS,
        "batches_per_epoch": steps,
        "step_ms": round(step_s * 1000, 3),
        "reader_delay_ms": round(delay_s * 1000, 3),
        "pipelined_wall_s": round(piped_wall, 3),
        "legacy_sync_wall_s": round(legacy_wall, 3),
        "depth0_wall_s": round(depth0_wall, 3),
        "speedup_vs_legacy": round(legacy_wall / piped_wall, 3),
        "loss_delta_pipelined_vs_depth0": float(loss_delta),
    }

    # -- overlap: staging hidden behind the consumer --------------------------
    ov_learner = learner(epochs=6, batch_size=128, prefetch_depth=4)
    ov_learner.fit(df)
    summaries = ov_learner._prefetch_summaries
    wait = sum(s["wait_s"] for s in summaries)
    prep = sum(s["prep_s"] for s in summaries)
    overlap = {
        "overlap_ratio": round(max(0.0, 1.0 - wait / max(prep, 1e-9)), 4),
        "per_epoch": [round(s["overlap_ratio"], 4) for s in summaries],
        "batches": int(sum(s["batches"] for s in summaries)),
        "overlapped_batches": int(
            sum(s["overlapped_batches"] for s in summaries)),
        "resident_bytes_peak": int(
            max(s["resident_bytes_peak"] for s in summaries)),
    }

    # -- counted-upload invariant ---------------------------------------------
    UP_EPOCHS = 2
    before = dataplane_counters().snapshot()
    learner(epochs=UP_EPOCHS, prefetch_depth=2).fit(df)
    after = dataplane_counters().snapshot()
    expected = UP_EPOCHS * steps * 3 + 1  # {x,y,w} per batch + train state
    uploads = {
        "h2d_transfers": int(after["h2d_transfers"] - before["h2d_transfers"]),
        "expected_transfers": expected,
        "leaves_per_batch": 3,
        "h2d_bytes": int(after["h2d_bytes"] - before["h2d_bytes"]),
        "d2h_transfers_in_fit": int(
            after["d2h_transfers"] - before["d2h_transfers"]),
    }
    uploads["exact"] = (
        uploads["h2d_transfers"] == expected
        and uploads["d2h_transfers_in_fit"] <= 1  # the epoch-end loss fetch
    )

    # -- device MFU from inside the epoch loop --------------------------------
    prof = device_profiler()
    mfu_label = f"tpu_learner:{D}"
    mfu_value = prof.mfu(mfu_label)
    mfu = {
        "model": mfu_label,
        "device_mfu": (
            round(mfu_value, 6) if mfu_value == mfu_value else None),
    }

    # -- gradient accumulation: deterministic rerun + parity band -------------
    acc_a = learner(epochs=3, accum_steps=4).fit(df)._loss_history
    acc_b = learner(epochs=3, accum_steps=4).fit(df)._loss_history
    acc_1 = learner(epochs=3, accum_steps=1).fit(df)._loss_history
    accumulation = {
        "accum_steps": 4,
        "rerun_delta": float(max(abs(a - b) for a, b in zip(acc_a, acc_b))),
        "parity_band_vs_accum1": float(
            max(abs(a - b) for a, b in zip(acc_a, acc_1))),
    }

    # -- out-of-core: streamed epochs at an 8x-chunk data budget --------------
    MN, MCH = 16384, 2048  # 8 chunks; each chunk is 1/8 of the dataset
    with tempfile.TemporaryDirectory() as shard_dir:
        my = rng.integers(0, 4, MN).astype(np.int64)
        mx = rng.normal(size=(MN, D)).astype(np.float32)
        cols = {f"f{i:02d}": np.ascontiguousarray(mx[:, i]) for i in range(D)}
        cols["label"] = my
        write_numpy_shards(shard_dir, cols, rows_per_shard=MCH)
        del mx, cols

        def ooc_learner():
            return TPULearner(mlp(D, [32], 4), epochs=1, batch_size=BS,
                              learning_rate=0.1, seed=7, shuffle=False)

        # warm both step shapes so tracemalloc sees steady-state data
        # movement, not compile-time allocations
        ooc_learner().fit_from_reader(NumpyShardReader(shard_dir,
                                                       chunk_rows=MCH))
        gc.collect()
        tracemalloc.start()
        ooc_learner().fit_from_reader(NumpyShardReader(shard_dir,
                                                       chunk_rows=MCH))
        _, streamed_peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()

        gc.collect()
        tracemalloc.start()
        rd = NumpyShardReader(shard_dir, chunk_rows=MCH)
        feat = sorted(c for c in rd.column_names if c != "label")
        full_x = np.concatenate(
            [c.matrix(feat, np.float32) for c in rd.iter_chunks()])
        full_y = np.concatenate(
            [np.rint(c.columns["label"]).astype(np.int64)
             for c in rd.iter_chunks()])
        ooc_learner().fit(
            DataFrame.from_dict({"features": full_x, "label": full_y}))
        _, inmem_peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        del full_x, full_y

    out_of_core = {
        "rows": MN,
        "chunk_rows": MCH,
        "chunks": MN // MCH,
        "streamed_peak_bytes": int(streamed_peak),
        "in_memory_peak_bytes": int(inmem_peak),
        "peak_ratio": round(streamed_peak / max(inmem_peak, 1), 4),
    }

    # -- kill at a checkpoint rename, resume with accumulation on -------------
    from mmlspark_tpu.io.storage_faults import (
        InjectedCrash,
        StorageFaultInjector,
        installed,
    )

    def recovery_fit(ckpt=None):
        reader = ArrayReader({"features": xv[:1024], "label": yv[:1024]},
                             chunk_rows=BS)
        return TPULearner(
            mlp(D, [16], CLASSES), epochs=4, batch_size=128,
            learning_rate=0.1, seed=7, shuffle=False, accum_steps=2,
        ).fit_from_reader(
            reader, checkpoint_dir=ckpt,
            checkpoint_every=2 if ckpt else None,
        )

    rec_baseline = recovery_fit()._loss_history
    with tempfile.TemporaryDirectory() as ckpt_dir:
        inj = StorageFaultInjector()
        inj.crash_after_rename(nth=1)
        crashed = False
        try:
            with installed(inj):
                recovery_fit(ckpt=ckpt_dir)
        except InjectedCrash:
            crashed = True
        resumed = recovery_fit(ckpt=ckpt_dir)._loss_history
    recovery = {
        "crash_injected": crashed,
        "accum_steps": 2,
        "resume_delta": float(
            max(abs(a - b) for a, b in zip(rec_baseline, resumed))),
    }

    report = {
        "pr": 18,
        "platform": jax.default_backend(),
        "n_devices": nd,
        "dnn_training": {
            "pipeline": pipeline,
            "overlap": overlap,
            "uploads": uploads,
            "mfu": mfu,
            "accumulation": accumulation,
            "out_of_core": out_of_core,
            "recovery": recovery,
        },
    }
    return _write_report(report, out_path)


def main() -> int:
    from mmlspark_tpu.dnn import resnet20_cifar

    imgs_per_sec, imgs_per_sec_resident = bench_cifar()
    r50_e2e, r50_resident, r50_flops = bench_resnet50()
    gbdt_adult = bench_gbdt()
    gbdt_1m = bench_gbdt_1m()
    p50, p99 = bench_serving()
    d_p50, d_p99, m_p50, m_p99, m_decomp = bench_distributed_serving()

    r20_flops = resnet20_cifar().flops_per_example()
    extras = {
        "cifar_device_resident_imgs_per_sec": round(imgs_per_sec_resident, 1),
        "resnet50_featurize_imgs_per_sec": round(r50_e2e, 1),
        "resnet50_device_resident_imgs_per_sec": round(r50_resident, 1),
        "serving_p50_ms": round(p50, 3),
        "serving_p99_ms": round(p99, 3),
        "serving_pool8_p50_ms": round(d_p50, 3),
        "serving_pool8_p99_ms": round(d_p99, 3),
        "serving_resnet20_p50_ms": round(m_p50, 3),
        "serving_resnet20_p99_ms": round(m_p99, 3),
        "serving_resnet20_stage_decomp": m_decomp,
    }
    # MFU lines omitted (not -1) on unknown device kinds, per peak_flops
    if peak_flops() > 0:
        extras["cifar_resident_mfu_percent"] = mfu(
            imgs_per_sec_resident, r20_flops
        )
        extras["resnet50_resident_mfu_percent"] = mfu(r50_resident, r50_flops)
    for name, cfg in (("gbdt_adult", gbdt_adult), ("gbdt_1m", gbdt_1m)):
        for k, v in cfg.items():
            extras[f"{name}_{k}"] = v

    print(
        json.dumps(
            {
                "metric": "cifar10_resnet20_inference",
                "value": round(imgs_per_sec, 1),
                "unit": "imgs/sec/chip",
                "vs_baseline": round(imgs_per_sec / V100_CNTK_IMGS_PER_SEC, 3),
                "extras": extras,
            }
        )
    )
    return 0


def run_compute_tier_smoke(out_path: str = "BENCH_pr19.json") -> dict:
    """Pallas compute-tier smoke bench (CPU interpret mode; wired into
    tier-1 via tests/test_bench_smoke.py::test_compute_tier_smoke_gates),
    written to BENCH_pr19.json. ISSUE 19 acceptance at CPU smoke scale:

    - **interpret parity**: trees grown with ``hist_impl="pallas"`` are
      BIT-IDENTICAL to ``hist_impl="einsum"`` on every engine (fused,
      data_parallel, streamed) — masked padding adds 0.0f to every
      histogram cell, so the kernelized route+hist is exact, not
      approximate; the Pallas split finder makes IDENTICAL decisions
      (feature + threshold) with gains in an f32-ulp band; fused Pallas
      scoring is bitwise identical to the reference walk; the int8
      dequant-in-VMEM matmul matches the XLA contraction to f32 ulps.
    - **int8 zoo parity**: int8 weight-only variants of a dense and a
      conv network match their f32 parents within INT8_LOGIT_MAE_TOL
      relative logit MAE with exact top-1 — the same gate shape as bf16.
    - **MFU attribution**: round flight records carry `hist_impl` +
      `flops_source` attrs, so pallas-vs-einsum MFU deltas are
      attributable in /debug/flight.

    HONEST-BASELINE NOTE on the timing rows: on this CPU box the Pallas
    arms run in INTERPRET mode — a correctness vehicle, not a fast path —
    so the recorded speedups are expected to be < 1x here. They are
    recorded for attribution (same measurement shape as a TPU round, where
    the MXU-tiled kernels are the point); the on-device MFU gate is
    TPU-only and documented in docs/gbdt.md "Pallas compute tier".
    """
    import dataclasses

    import jax

    from mmlspark_tpu.dnn.network import Network, NetworkBundle
    from mmlspark_tpu.dnn.quant import int8_matmul, quantize_per_channel
    from mmlspark_tpu.dnn.zoo_builders import INT8_LOGIT_MAE_TOL, int8_variant
    from mmlspark_tpu.gbdt import trainer as trainer_mod
    from mmlspark_tpu.gbdt.compute import best_splits_for_hists
    from mmlspark_tpu.gbdt.objectives import make_objective
    from mmlspark_tpu.gbdt.trainer import TrainConfig, train_booster
    from mmlspark_tpu.obs.profiler import device_profiler

    nd = jax.device_count()
    if nd < 8:
        return {"skipped": True, "n_devices": nd,
                "reason": "needs XLA_FLAGS=--xla_force_host_platform_"
                          "device_count=8 (set before jax import)"}

    n, F = 8_192, 24
    rng = np.random.default_rng(19)
    x = rng.normal(size=(n, F))
    y = (x[:, 0] + 0.5 * x[:, 1] - 0.3 * x[:, 2]
         + rng.normal(scale=0.5, size=n) > 0).astype(np.float64)
    obj = make_objective("binary", num_class=2)
    base = TrainConfig(num_iterations=3, num_leaves=9, max_bin=31,
                      verbosity=0)

    def fit(engine, hist_impl, stream=0, single=False):
        cfg = dataclasses.replace(base, engine=engine, hist_impl=hist_impl)
        if single:
            # fused in-memory under the GSPMD program can't host
            # pallas_call — force the single-device fused path (same
            # switch bench.run_sharded_gbdt_smoke uses) so the kernel
            # actually engages on this 8-virtual-device mesh
            trainer_mod._FORCE_SINGLE_DEVICE = True
        try:
            return train_booster(x, y, obj, cfg,
                                 stream_chunk_rows=stream)
        finally:
            trainer_mod._FORCE_SINGLE_DEVICE = False

    # -- route+hist exactness: trees bit-identical per engine -------------
    arms = {
        "fused": dict(engine="fused", single=True),
        "data_parallel": dict(engine="data_parallel"),
        "streamed": dict(engine="data_parallel", stream=2048),
    }
    trees_identical, boost_walls = {}, {}
    b_fused_pallas = None
    for name, kw in arms.items():
        walls = {}
        for impl in ("pallas", "einsum"):
            fit(hist_impl=impl, **kw)  # warm: trace/compile once
            t0 = time.perf_counter()
            b = fit(hist_impl=impl, **kw)
            walls[impl] = round(time.perf_counter() - t0, 3)
            if impl == "pallas":
                bp = b
                if name == "fused":
                    b_fused_pallas = b
            else:
                be = b
        trees_identical[name] = bp.model_to_string() == be.model_to_string()
        boost_walls[name] = walls

    # -- Pallas split finder vs jitted-vmap reference ----------------------
    M, Fs, B = 16, 64, 32
    rng2 = np.random.default_rng(3)
    cnt = rng2.integers(1, 50, size=(M, Fs, B)).astype(np.float32)
    hists = np.stack([
        rng2.normal(size=(M, Fs, B)).astype(np.float32) * cnt,
        rng2.uniform(0.1, 1.0, size=(M, Fs, B)).astype(np.float32) * cnt,
        cnt,
    ], axis=-1)
    n_bins_arr = np.full(Fs, B, np.int32)
    cat_arr = np.zeros(Fs, bool)
    fmask = np.ones(Fs, bool)
    scal = [np.float32(1.0), np.float32(1e-3), np.float32(0.0),
            np.float32(1.0)]
    split_args = dict(num_bins=B, max_cat_threshold=32,
                      cat_static=tuple([False] * Fs))

    def find(impl):
        return best_splits_for_hists(
            hists, True, n_bins_arr, cat_arr, fmask, *scal,
            split_impl=impl, **split_args)

    def timed(fn, repeats=10):
        fn()  # warm
        t0 = time.perf_counter()
        for _ in range(repeats):
            out = fn()
        np.asarray(out[0])
        return (time.perf_counter() - t0) / repeats

    ref = [np.asarray(a) for a in find("reference")]
    ker = [np.asarray(a) for a in find("pallas")]
    decisions_identical = bool(
        np.array_equal(ref[1], ker[1]) and np.array_equal(ref[2], ker[2]))
    gain_rel = float(np.max(
        np.abs(ref[0] - ker[0]) / np.maximum(np.abs(ref[0]), 1e-6)))
    t_ref = timed(lambda: find("reference"))
    t_ker = timed(lambda: find("pallas"))

    # -- fused Pallas scoring vs reference walk ----------------------------
    xs = x[:4096].astype(np.float32)
    walk = {}
    for impl in ("raw", "pallas"):
        b_fused_pallas._walk_impl = impl
        b_fused_pallas.predict_raw(xs)  # warm
        t0 = time.perf_counter()
        walk[impl] = np.asarray(b_fused_pallas.predict_raw(xs))
        walk[impl + "_s"] = time.perf_counter() - t0
    b_fused_pallas._walk_impl = "auto"
    scoring_bitwise = bool(np.array_equal(walk["raw"], walk["pallas"]))

    # -- int8 matmul kernel vs the XLA contraction -------------------------
    xm = rng.normal(size=(64, 200)).astype(np.float32)
    wm = rng.normal(size=(200, 96)).astype(np.float32)
    q, scale = quantize_per_channel(wm)
    got = np.asarray(int8_matmul(xm, q, scale))
    want = (xm @ q.astype(np.float32)) * scale[None, :]
    mm_delta = float(np.max(np.abs(got - want)))

    # -- int8 zoo parity (the bf16 gate's shape: rel MAE + exact top-1) ----
    def int8_parity(spec, in_shape, xin):
        net = Network(spec, input_shape=in_shape)
        f32 = NetworkBundle(net, net.init(jax.random.PRNGKey(0)))
        i8 = int8_variant(f32)
        ref = np.asarray(f32.network.apply(f32.variables, xin))
        got = np.asarray(i8.network.apply(i8.variables, xin))
        mae = float(np.mean(np.abs(ref - got)) / max(np.mean(np.abs(ref)),
                                                     1e-12))
        top1 = bool(np.array_equal(ref.argmax(1), got.argmax(1)))
        return {"rel_logit_mae": round(mae, 5), "top1_exact": top1}

    mlp = int8_parity(
        [{"kind": "dense", "name": "d0", "units": 128},
         {"kind": "relu", "name": "r0"},
         {"kind": "dense", "name": "d1", "units": 10}],
        (32,), rng.normal(size=(64, 32)).astype(np.float32))
    conv = int8_parity(
        [{"kind": "conv", "name": "c0", "filters": 8, "kernel": 3},
         {"kind": "relu", "name": "r0"},
         {"kind": "flatten", "name": "f"},
         {"kind": "dense", "name": "d0", "units": 10}],
        (16, 16, 3), rng.normal(size=(16, 16, 16, 3)).astype(np.float32))

    # -- MFU attribution rows in the flight ring ---------------------------
    recs = device_profiler().flight()["records"]
    by_impl = {"pallas": 0, "einsum": 0}
    for r in recs:
        attrs = r.get("attrs") or {}
        impl = attrs.get("hist_impl")
        if impl in by_impl and r.get("flops_source") == "analytic":
            by_impl[impl] += 1

    report = {
        "pr": 19,
        "n_devices": nd,
        "config": {
            "rows": n, "features": F, "iterations": base.num_iterations,
            "num_leaves": base.num_leaves, "max_bin": base.max_bin,
            "split_bench": {"leaves": M, "features": Fs, "bins": B},
        },
        "interpret_parity": {
            "trees_bit_identical": trees_identical,
            "split_finder": {
                "decisions_identical": decisions_identical,
                "gain_max_rel_delta": gain_rel,
            },
            "scoring": {"bitwise_identical": scoring_bitwise},
            "int8_matmul_max_abs_delta": mm_delta,
        },
        "timings": {
            "note": "CPU interpret mode: the Pallas arms execute the "
                    "kernel bodies through the interpreter — a "
                    "correctness vehicle, expected SLOWER than the "
                    "XLA reference here; recorded for attribution, "
                    "gated on TPU only (docs/gbdt.md)",
            "boost_wall_s": boost_walls,
            "split_finder": {
                "reference_s": round(t_ref, 5),
                "pallas_interpret_s": round(t_ker, 5),
                "speedup": round(t_ref / max(t_ker, 1e-9), 3),
            },
            "scoring": {
                "raw_s": round(walk["raw_s"], 4),
                "pallas_interpret_s": round(walk["pallas_s"], 4),
                "speedup": round(walk["raw_s"] / max(walk["pallas_s"],
                                                     1e-9), 3),
            },
        },
        "int8": {
            "tolerance": INT8_LOGIT_MAE_TOL,
            "mlp": mlp,
            "conv": conv,
        },
        "mfu_attribution": {
            "pallas_rows": by_impl["pallas"],
            "einsum_rows": by_impl["einsum"],
            "read_via": "/debug/flight record attrs.hist_impl + "
                        "flops_source",
        },
        "mfu_gate": {
            "tpu_only": True,
            "note": "hist-pass MFU under hist_impl=pallas >= the einsum "
                    "arm's is asserted on TPU hardware only "
                    "(tests/test_tpu_kernels.py); interpret mode has no "
                    "meaningful MFU",
        },
    }
    return _write_report(report, out_path)


if __name__ == "__main__":
    if "--force" in sys.argv[1:]:
        # the clobber guard's escape hatch: intentionally record a round
        # even when it fails the bench's own tier-1 gates
        _FORCE_WRITE = True
    if "--smoke" in sys.argv[1:]:
        # the CPU-safe smoke tier runs on the SAME 8-virtual-device mesh
        # the tier-1 suite forces (tests/conftest.py), so standalone
        # `bench.py --smoke` rounds and committed artifacts share one
        # environment; must happen before the first jax import
        import os as _os

        _os.environ.setdefault("JAX_PLATFORMS", "cpu")
        _flags = _os.environ.get("XLA_FLAGS", "")
        if (
            _os.environ["JAX_PLATFORMS"] == "cpu"
            and "xla_force_host_platform_device_count" not in _flags
        ):
            _os.environ["XLA_FLAGS"] = (
                _flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        print(json.dumps(run_smoke(), sort_keys=True))
        print(json.dumps(run_serving_smoke(), sort_keys=True))
        print(json.dumps(run_obs_overhead_smoke(), sort_keys=True))
        print(json.dumps(run_fault_smoke(), sort_keys=True))
        print(json.dumps(run_image_prep_smoke(), sort_keys=True))
        print(json.dumps(run_recovery_smoke(), sort_keys=True))
        print(json.dumps(run_streaming_smoke(), sort_keys=True))
        print(json.dumps(run_profiler_smoke(), sort_keys=True))
        print(json.dumps(run_slo_trace_smoke(), sort_keys=True))
        print(json.dumps(run_sharded_gbdt_smoke(), sort_keys=True))
        print(json.dumps(run_memory_smoke(), sort_keys=True))
        print(json.dumps(run_federation_smoke(), sort_keys=True))
        print(json.dumps(run_dnn_training_smoke(), sort_keys=True))
        print(json.dumps(run_compute_tier_smoke(), sort_keys=True))
        sys.exit(0)
    sys.exit(main())
