"""Benchmark entry point — run by the driver on real TPU hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Measures the BASELINE.json headline config: CIFAR10-shape ResNet-20 batch
inference through the full product path (DataFrame -> TPUModel.transform ->
scores column), i.e. the CNTKModel CIFAR10 notebook flow
(reference: CNTKModel.scala:469-516). Steady-state, compile excluded.

vs_baseline: the reference publishes no absolute numbers (SURVEY.md §6), so
the bar is BASELINE.md's north star — ">= 1x V100 wall-clock". We use a
nominal 6,000 imgs/sec for V100-era CNTK ResNet-20 batched eval (documented
estimate in BASELINE.md; the reference's own per-row JNI path was far below
this). vs_baseline = measured / 6000.
"""

import json
import sys
import time

import numpy as np

V100_CNTK_IMGS_PER_SEC = 6000.0  # documented estimate, see BASELINE.md

N_IMAGES = 16384
BATCH = 8192
REPEATS = 3


def main() -> int:
    import jax

    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.dnn import resnet20_cifar
    from mmlspark_tpu.dnn.network import NetworkBundle
    from mmlspark_tpu.models import TPUModel

    rng = np.random.default_rng(0)
    # uint8 pixels, CIFAR layout: the realistic wire format (4x less
    # host->HBM traffic than f32; normalization happens on device)
    imgs = rng.integers(0, 256, size=(N_IMAGES, 32 * 32 * 3), dtype=np.uint8)
    df = DataFrame.from_dict({"images": imgs})

    net = resnet20_cifar(num_classes=10, compute_dtype="bfloat16")
    variables = net.init(jax.random.PRNGKey(0))
    model = TPUModel(
        NetworkBundle(net, variables),
        input_col="images",
        output_col="scores",
        mini_batch_size=BATCH,
    )

    model.transform(df.limit(BATCH))  # compile + warmup

    best = 0.0
    for _ in range(REPEATS):
        t0 = time.time()
        out = model.transform(df)
        dt = time.time() - t0
        best = max(best, N_IMAGES / dt)
    assert out["scores"].shape == (N_IMAGES, 10)

    print(
        json.dumps(
            {
                "metric": "cifar10_resnet20_inference",
                "value": round(best, 1),
                "unit": "imgs/sec/chip",
                "vs_baseline": round(best / V100_CNTK_IMGS_PER_SEC, 3),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
